"""Health-gated routing + mid-stream migration over supervised replicas
(ISSUE 13 tentpole).

PRs 6/7 made ONE engine survive faults; PRs 11/12 gave it a TP runner
and an async front-end — but the service was still one process, and a
dead engine thread took every in-flight stream with it. This module is
the replica-resilience layer above the PR 12 front-end:

* **Supervision.** A monitor thread heartbeats every
  :class:`~paddle_tpu.serving.replica.Replica` (liveness + the
  ``heartbeat-drop``/``replica-crash`` fault points), and restarts dead
  ones with exponential backoff (``paddle_tpu_replica_restarts_total``)
  while their streams migrate away.
* **Health-gated routing.** New streams go to the least-loaded READY
  replica (readiness = the ``/readyz`` semantics: not draining,
  watchdog below its degradation threshold, queue depth in bounds); a
  live-but-degraded replica keeps its in-flight work and takes nothing
  new. With nothing ready, placement falls back to any live replica
  (shedding to nowhere helps nobody), then retries with bounded
  backoff before failing the request attributably.
* **Integrity quarantine (ISSUE 14).** A live replica reporting
  ``quarantined`` (its weight audit caught silent corruption; the
  engine has already fail-stopped) is fenced like a crash, only
  sooner: the sweep kills it FIRST — before anything could route to
  it — then the ordinary dead-replica machinery migrates its streams
  (every delivered token predates the corruption, so resume-from-
  emitted is still bit-exact) and supervised-restarts it with freshly
  verified weights (``paddle_tpu_replica_quarantines_total``).
* **Mid-stream migration (KV-free).** The router records each stream's
  prompt + every emitted token id. When a replica dies mid-stream —
  broken transport (the SIGKILL signature), heartbeat loss, or a stream
  stalled past ``stall_s`` — the stream re-admits on a healthy replica
  as prompt‖emitted via the engine's resume-from-emitted path
  (``Engine.add_request(resume_tokens=...)``): the prefix cache absorbs
  the recompute, only the continuation streams back, and the router
  splices it so the client sees ONE uninterrupted, bit-identical token
  sequence (greedy by construction; seeded-sampled via the replayed key
  schedule). No KV ever crosses replicas — the DistServe/Mooncake-style
  re-prefill trade: recompute one prefix vs checkpointing every page.
* **Bounded retry + single hedge.** Every re-placement loop is attempt-
  bounded with backoff (tpulint TPL902 enforces the shape tree-wide);
  optionally a stream whose FIRST token is slower than ``hedge_ms``
  gets ONE duplicate on another replica — first chunk wins, the loser
  is cancelled (greedy streams are identical on both, so the race is
  free of divergence).

Metrics: ``paddle_tpu_router_migrations_total``,
``paddle_tpu_replica_restarts_total``, ``paddle_tpu_router_hedges_total``,
``paddle_tpu_router_replicas_ready`` — the bench_failover block and the
chaos suite assert on these.

Client callbacks fire from replica-owned threads; RouterTicket does the
locking. Stdlib-only (tickets mirror StreamTicket's surface, so the
SLO load generator drives a Router exactly like a ServingFrontend).

ISSUE 20 layers :mod:`~paddle_tpu.serving.cluster` above this router:
``Router(pools={"prefill": k, "decode": m})`` activates role pools,
cross-replica KV handoff between the prefill and decode legs, and
prefix-cache-aware placement. The router keeps owning supervision,
migration, and retry; the coordinator only SHAPES placements (role
filter, cache scoring, prefill budget cap) and intercepts clean prefill
completions to continue them on the decode pool. TTFT hedging is
disabled in pool mode — a hedge duplicates the FULL spec, which would
put prefill-sized work back on decode replicas.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..observability import counter, gauge
from ..observability.tracing import TRACER as _TRACER
from ..observability.tracing import flight_record as _flight_record
from ..testing.faultinject import FaultPlan
from .replica import Replica, ReplicaStream, StreamSpec

__all__ = ["Router", "RouterTicket", "REPLICA_LOST"]

# the router-level failure slug (labels request_failures_total like the
# engine taxonomy's reason slugs — treat as stable)
REPLICA_LOST = "replica_lost"


class RouterTicket:
    """The client's stream handle across replica deaths: accumulates
    the FULL emitted sequence (pre- and post-migration), forwards fresh
    chunks to ``on_chunk``, and exposes the same result/latency surface
    as :class:`~paddle_tpu.serving.frontend.StreamTicket` so load
    generators drive a router unchanged."""

    def __init__(self, spec: StreamSpec,
                 on_chunk: Optional[Callable] = None):
        self.spec = spec
        self.prompt = spec.prompt
        self.max_new_tokens = spec.max_new_tokens
        self.tokens: List[int] = []
        self.done = False
        self.failure_reason: Optional[str] = None
        self.cancelled = False
        self.migrations = 0
        self.hedged = False
        # cluster phase (ISSUE 20): None outside pool mode, else
        # "prefill" -> "handoff" -> "decode"; written under _cond (the
        # coordinator's handoff thread and _place both touch it)
        self.phase: Optional[str] = None
        self.replica: Optional[str] = None  # current host replica name
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.last_progress = self.t_submit
        self._on_chunk = on_chunk
        # request tracing (ISSUE 18): the trace ROOT span — minted by
        # Router.submit, ended when the ticket reaches a terminal state;
        # every hop (replicas included, via spec.trace) nests under it
        self._root = None
        self._cond = threading.Condition()
        # sources authorized to deliver into this ticket. Before the
        # first chunk several may race (a hedge); the first to deliver
        # becomes _primary and the rest are cancelled. After migration
        # the fresh source is primary immediately (it resumes exactly
        # where the dead one stopped).
        self._srcs: List[ReplicaStream] = []
        self._primary: Optional[ReplicaStream] = None

    # ------------------------------------------------- replica callbacks
    def _deliver(self, src: ReplicaStream, toks: List[int]) -> bool:
        """Accept a chunk from ``src`` if it is (or becomes) the
        primary source; returns losers False so the router can cancel
        them."""
        cancel_losers: List[ReplicaStream] = []
        with self._cond:
            if self.done or src not in self._srcs:
                return False
            if self._primary is None:
                self._primary = src
                cancel_losers = [s for s in self._srcs if s is not src]
                self._srcs = [src]
            elif src is not self._primary:
                return False
            now = time.perf_counter()
            if self.t_first is None:
                self.t_first = now
            self.last_progress = now
            self.tokens.extend(int(t) for t in toks)
            self._cond.notify_all()
        for s in cancel_losers:
            s.cancel()
        if self._on_chunk is not None:
            self._on_chunk(list(toks))
        return True

    def _finish(self, failure_reason: Optional[str] = None):
        with self._cond:
            if self.done:
                return
            self.done = True
            self.failure_reason = failure_reason
            self.t_done = time.perf_counter()
            self._srcs = []
            self._primary = None
            self._cond.notify_all()
            # claim the trace root while still holding the lock (the
            # done-gate above already serializes finishers, but the
            # submit-side write holds _cond too, so ALL _root writes
            # share one lock — tpurace TPL1501/TPL1503); end() runs
            # outside: it may flush an exporter
            root, self._root = self._root, None
        if root is not None:
            root.end(tokens=len(self.tokens),
                     migrations=self.migrations,
                     failure=failure_reason)
        if self._on_chunk is not None:
            self._on_chunk(None)

    # ----------------------------------------------------- migration aid
    def _detach(self, src: ReplicaStream) -> Optional[List[int]]:
        """Remove a (dead) source; returns the emitted-token snapshot
        to resume from when the ticket still needs a new home, None
        when this source wasn't load-bearing (already finished, or a
        raced-out hedge loser)."""
        with self._cond:
            if self.done or src not in self._srcs:
                return None
            self._srcs.remove(src)
            if self._primary is src:
                self._primary = None
            elif self._srcs:
                return None  # a live source remains (hedge partner)
            return list(self.tokens)

    def _attach(self, src: ReplicaStream, primary: bool):
        with self._cond:
            if self.done:
                return
            self._srcs.append(src)
            if primary:
                self._primary = src

    def stalled_s(self, now: Optional[float] = None) -> float:
        with self._cond:
            if self.done:
                return 0.0
            return (now or time.perf_counter()) - self.last_progress

    # --------------------------------------------------- consumer surface
    def result(self, timeout: Optional[float] = None) -> List[int]:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while not self.done:
                left = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                if left == 0.0 or not self._cond.wait(left):
                    raise TimeoutError("stream did not terminate in time")
            return list(self.tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        return (None if self.t_first is None
                else self.t_first - self.t_submit)

    @property
    def tpot_s(self) -> Optional[float]:
        if self.t_first is None or self.t_done is None \
                or len(self.tokens) <= 1:
            return None
        return (self.t_done - self.t_first) / (len(self.tokens) - 1)


class Router:
    """See module docstring. ``replicas`` are started (if needed) by
    ``start()``; ``shutdown()`` stops the monitor and (optionally) the
    replicas."""

    def __init__(self, replicas: List[Replica], fault_plan=None,
                 heartbeat_s: float = 0.1,
                 stall_s: Optional[float] = 30.0,
                 hedge_ms: Optional[float] = None,
                 max_place_attempts: int = 5,
                 place_backoff_s: float = 0.05,
                 max_migrations: int = 3,
                 restart_dead: bool = True,
                 restart_backoff_s: float = 0.2,
                 restart_backoff_cap_s: float = 5.0,
                 pools: Optional[Dict[str, int]] = None,
                 replica_factory: Optional[Callable] = None,
                 handoff_budget_s: float = 5.0,
                 autoscale: Optional[Dict] = None):
        if not replicas:
            raise ValueError("Router needs at least one replica")
        self.replicas = list(replicas)
        self._fi = FaultPlan.from_spec(fault_plan)
        self.heartbeat_s = float(heartbeat_s)
        self.stall_s = None if stall_s is None else float(stall_s)
        self.hedge_ms = None if hedge_ms is None else float(hedge_ms)
        self.max_place_attempts = int(max_place_attempts)
        self.place_backoff_s = float(place_backoff_s)
        self.max_migrations = int(max_migrations)
        self.restart_dead = bool(restart_dead)
        self.restart_backoff_s = float(restart_backoff_s)
        self.restart_backoff_cap_s = float(restart_backoff_cap_s)
        self._tickets: set = set()
        self._dead: Dict[int, float] = {}   # replica idx -> death time
        self._restarting: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._m_migrations = counter(
            "paddle_tpu_router_migrations_total",
            "in-flight streams migrated to another replica "
            "(resume-from-emitted re-admission)")
        self._m_restarts = counter(
            "paddle_tpu_replica_restarts_total",
            "dead replicas restarted by the router's supervisor")
        self._m_hedges = counter(
            "paddle_tpu_router_hedges_total",
            "TTFT hedges launched (duplicate stream on a second "
            "replica; first chunk wins)")
        self._m_failures = counter(
            "paddle_tpu_request_failures_total",
            "requests moved to terminal FAILED, by taxonomy reason and "
            "tenant", labelnames=("reason", "tenant"))
        self._m_ready = gauge(
            "paddle_tpu_router_replicas_ready",
            "replicas currently passing the readiness gate")
        self._m_quarantines = counter(
            "paddle_tpu_replica_quarantines_total",
            "replicas fenced off after an integrity-audit failure "
            "(weight corruption): streams migrated, replica killed and "
            "supervised-restarted with verified weights")
        # cluster mode (ISSUE 20): pools activates the coordinator;
        # without it every path below is byte-for-byte PR 13 behavior
        self.cluster = None
        if pools:
            from .cluster import ClusterCoordinator
            self.cluster = ClusterCoordinator(
                self, dict(pools), replica_factory=replica_factory,
                handoff_budget_s=handoff_budget_s, autoscale=autoscale)

    # ------------------------------------------------------------ control
    def start(self) -> "Router":
        for rep in self.replicas:
            if not rep.alive():
                rep.start()
        if self._monitor is None:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="paddle-router-monitor",
                daemon=True)
            self._monitor.start()
        return self

    def shutdown(self, stop_replicas: bool = True):
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
        if stop_replicas:
            for rep in self.replicas:
                try:
                    rep.stop()
                except Exception:
                    pass

    # ------------------------------------------------------------ routing
    def _ready_replicas(self) -> List[Replica]:
        out = []
        for idx, rep in enumerate(self.replicas):
            with self._lock:
                if idx in self._dead:
                    continue
            if self.cluster is not None and self.cluster.is_drained(idx):
                continue
            try:
                if rep.alive() and rep.ready().get("ready"):
                    out.append(rep)
            except Exception:
                continue
        return out

    def _pick(self, exclude=(), role: Optional[str] = None,
              spec: Optional[StreamSpec] = None) -> Optional[Replica]:
        """Least-loaded READY replica, falling back to any live one:
        when every survivor is degraded, routing to a degraded replica
        still beats failing the request. In pool mode ``role`` narrows
        to that pool (an empty/unready pool borrows cross-role —
        availability beats purity) and ``spec`` upgrades the pick to
        the coordinator's prefix-overlap scoring."""
        ready = [r for r in self._ready_replicas() if r not in exclude]
        if self.cluster is not None and role is not None:
            pool = [r for r in ready if self.cluster.role_of(r) == role]
            if pool:
                ready = pool
        if not ready:
            with self._lock:
                dead = set(self._dead)
            ready = [r for i, r in enumerate(self.replicas)
                     if r not in exclude and i not in dead and r.alive()
                     and not (self.cluster is not None
                              and self.cluster.is_drained(i))]
        if not ready:
            return None
        if self.cluster is not None and spec is not None:
            return self.cluster.choose(ready, spec)
        return min(ready, key=lambda r: r.inflight)

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, seed: Optional[int] = None,
               tenant: Optional[str] = None,
               deadline_s: Optional[float] = None,
               on_chunk: Optional[Callable] = None) -> RouterTicket:
        """Route a new stream (ServingFrontend-compatible surface).
        Never raises on placement trouble: a ticket that cannot be
        placed after the bounded retry fails attributably with reason
        ``replica_lost``."""
        spec = StreamSpec([int(t) for t in list(prompt)], max_new_tokens,
                          temperature=temperature, seed=seed,
                          tenant=tenant, deadline_s=deadline_s)
        ticket = RouterTicket(spec, on_chunk=on_chunk)
        # trace root (ISSUE 18): minted HERE, at the outermost hop; the
        # wire context + origin clock ride the spec through placement,
        # hedges, and migrations, so the whole stream — both replicas
        # of a migrated one — renders as one contiguous trace
        spec.t_origin = ticket.t_submit
        if _TRACER.enabled:
            # under the ticket's condition like every later _root touch
            # (tpurace TPL1501: the monitor thread finishes tickets)
            with ticket._cond:
                ticket._root = _TRACER.start(
                    "request", "router", tenant=tenant or "default",
                    prompt_len=len(spec.prompt),
                    max_new_tokens=int(max_new_tokens))
                spec.trace = ticket._root.ctx.encode()
        with self._lock:
            self._tickets.add(ticket)
        self._place(ticket, resume=None, exclude=())
        return ticket

    def cancel(self, ticket: RouterTicket):
        ticket.cancelled = True
        with ticket._cond:
            srcs = list(ticket._srcs)
        for s in srcs:
            s.cancel()
        ticket._finish("cancelled")

    # ---------------------------------------------------------- placement
    def _place(self, ticket: RouterTicket, resume: Optional[List[int]],
               exclude=()):
        """(Re)admit ``ticket`` somewhere healthy: bounded attempts with
        backoff (TPL902's required shape), resume-from-emitted when
        ``resume`` carries the dead replica's delivered tokens."""
        spec = ticket.spec
        sub = StreamSpec(spec.prompt, spec.max_new_tokens,
                         temperature=spec.temperature, seed=spec.seed,
                         tenant=spec.tenant, deadline_s=spec.deadline_s,
                         resume_tokens=resume,
                         # same trace + origin clock on every
                         # (re)placement: a migrated stream's spans on
                         # the new replica join the ORIGINAL trace
                         trace=spec.trace, t_origin=spec.t_origin)
        role = None
        if self.cluster is not None:
            # pool mode: pick the role pool and (for a fresh prompt
            # worth disaggregating) cap the prefill leg to one token
            sub, role = self.cluster.outbound(ticket, sub)
        place = _TRACER.start(
            "router.place", "router", parent=spec.trace,
            resumed=len(resume or ())) if _TRACER.enabled else None
        last_exc: Optional[BaseException] = None
        for attempt in range(self.max_place_attempts):
            if ticket.done:
                if place is not None:
                    place.end(outcome="ticket-done", attempts=attempt)
                return
            if attempt:
                # backoff between attempts; the first try is immediate
                # (failover latency is the product here)
                time.sleep(min(1.0, self.place_backoff_s * (2 **
                                                            (attempt - 1))))
            rep = self._pick(exclude=exclude if attempt == 0 else (),
                             role=role, spec=sub)
            if rep is None:
                continue
            # two-phase submit: wire the stream to the ticket BEFORE
            # launching, so a replica fast enough to emit its first
            # chunk immediately can never race the attach and drop it
            stream = rep.prepare(sub, self._on_chunk, self._on_done,
                                 self._on_broken)
            stream._ticket = ticket
            ticket._attach(stream, primary=resume is not None)
            ticket.replica = rep.name
            # fresh stall budget for the new home (a migration storm
            # must not count the dead replica's silence against the
            # live one); under _cond like the delivery-side write
            # (tpurace TPL1501)
            with ticket._cond:
                ticket.last_progress = time.perf_counter()
            try:
                rep.launch(stream)
            except Exception as e:
                last_exc = e
                stream.cancel()
                ticket._detach(stream)
                continue
            if place is not None:
                place.end(outcome="placed", replica=rep.name,
                          attempts=attempt + 1)
            return
        if place is not None:
            place.end(outcome="failed", attempts=self.max_place_attempts)
        self._fail(ticket, REPLICA_LOST, last_exc)

    def _fail(self, ticket: RouterTicket, reason: str,
              exc: Optional[BaseException] = None):
        del exc  # attributable via logs/metrics only; the slug is the API
        self._m_failures.labels(
            reason=reason, tenant=ticket.spec.tenant or "default").inc()
        with self._lock:
            self._tickets.discard(ticket)
        ticket._finish(reason)

    # ------------------------------------------------- replica callbacks
    def _on_chunk(self, stream: ReplicaStream, toks: List[int]):
        ticket = getattr(stream, "_ticket", None)
        if ticket is not None:
            ticket._deliver(stream, toks)

    def _on_done(self, stream: ReplicaStream,
                 failure_reason: Optional[str]):
        ticket = getattr(stream, "_ticket", None)
        if ticket is None:
            return
        with ticket._cond:
            load_bearing = (stream in ticket._srcs
                            and (ticket._primary is None
                                 or ticket._primary is stream))
        if not load_bearing:
            return  # a cancelled hedge loser reporting in
        if self.cluster is not None:
            if failure_reason is None \
                    and self.cluster.intercept_done(stream, ticket):
                return  # prefill leg done; the handoff continues it
            self.cluster.note_done(ticket)
        with self._lock:
            self._tickets.discard(ticket)
        ticket._finish(failure_reason)

    def _on_broken(self, stream: ReplicaStream, exc: BaseException):
        """Transport died mid-stream (the SIGKILL/poison signature):
        migrate NOW — don't wait for the heartbeat to notice."""
        self._migrate_stream(stream, why=f"broken: {exc}")

    # ---------------------------------------------------------- migration
    def _migrate_stream(self, stream: ReplicaStream, why: str = ""):
        ticket = getattr(stream, "_ticket", None)
        if ticket is None or ticket.done:
            return
        resume = ticket._detach(stream)
        if resume is None:
            return  # not load-bearing (hedge partner still live)
        if ticket.migrations >= self.max_migrations:
            self._fail(ticket, REPLICA_LOST)
            return
        ticket.migrations += 1
        self._m_migrations.inc()
        if _TRACER.enabled:
            _TRACER.instant("router.migrate", "router",
                            parent=ticket.spec.trace,
                            from_replica=stream.replica.name,
                            why=why, emitted=len(resume),
                            migration=ticket.migrations)
        # make sure the old upstream can't keep emitting into a client
        # the new one now owns (harmless for a dead replica, essential
        # for a heartbeat-dropped one that is secretly still alive)
        stream.cancel()
        self._place(ticket, resume=resume,
                    exclude=(stream.replica,))

    def _migrate_replica(self, rep: Replica):
        for stream in rep.streams():
            self._migrate_stream(stream, why="replica dead")

    # --------------------------------------------------------- supervisor
    def _restart(self, idx: int, rep: Replica):
        """Restart a dead replica off the monitor thread (an engine
        rebuild compiles for seconds — the watchdog must keep watching
        the others meanwhile)."""
        delay = min(self.restart_backoff_cap_s,
                    self.restart_backoff_s * (2 ** min(rep.restarts, 8)))
        self._stop.wait(delay)
        try:
            if not self._stop.is_set():
                rep.restart()
                self._m_restarts.inc()
        except Exception:
            pass  # still dead; the next sweep schedules another attempt
        finally:
            with self._lock:
                self._restarting.discard(idx)
                if rep.alive():
                    self._dead.pop(idx, None)

    def _sweep(self):
        """One supervisor tick: fault points, liveness/heartbeat, stream
        stall watchdog, hedging, restart scheduling."""
        now = time.perf_counter()
        ready_count = 0
        for idx, rep in enumerate(self.replicas):
            if self.cluster is not None and self.cluster.is_drained(idx):
                continue  # autoscale-drained: stopped on purpose
            if self._fi is not None and self._fi.fire("replica-crash",
                                                      rid=idx):
                rep.kill()
            up = rep.alive() and rep.heartbeat(self._fi)
            if up:
                # integrity quarantine (ISSUE 14 containment ladder,
                # weight arm): a live replica whose weight audit failed
                # is WORSE than a dead one — every token it would still
                # produce flows through corrupt weights. Fence it FIRST
                # (kill — the poison/SIGKILL surface), then let the
                # normal dead-replica machinery below migrate its
                # streams (resume-from-emitted, bit-identical) and
                # schedule the supervised restart, which reloads
                # verified weights through the replica factory.
                try:
                    quarantined = bool(rep.ready().get("quarantined"))
                except Exception:
                    quarantined = False
                if quarantined:
                    self._m_quarantines.inc()
                    if _TRACER.enabled:
                        _TRACER.instant("router.quarantine", "fault",
                                        replica=rep.name)
                        _flight_record(f"replica-quarantine-{rep.name}")
                    rep.kill()
                    up = False
            with self._lock:
                was_dead = idx in self._dead
                if not up and not was_dead:
                    self._dead[idx] = now
                newly_dead = not up and not was_dead
                if up and was_dead and idx not in self._restarting:
                    self._dead.pop(idx, None)
            if newly_dead:
                if _TRACER.enabled:
                    # crash postmortem (ISSUE 18): for in-process
                    # replicas the shared ring still holds the victim's
                    # last decode steps — dump BEFORE migration churn
                    # overwrites them
                    _TRACER.instant("router.replica_dead", "fault",
                                    replica=rep.name)
                    _flight_record(f"replica-dead-{rep.name}")
                self._migrate_replica(rep)
            if not up and self.restart_dead:
                # (re)schedule the supervised restart: also re-arms
                # when a previous restart attempt itself failed
                with self._lock:
                    schedule = idx not in self._restarting
                    if schedule:
                        self._restarting.add(idx)
                if schedule:
                    threading.Thread(
                        target=self._restart, args=(idx, rep),
                        name=f"replica-restart-{rep.name}",
                        daemon=True).start()
            elif up:
                try:
                    payload = rep.ready()
                    if payload.get("ready"):
                        ready_count += 1
                    if self.cluster is not None:
                        # feed the placement view (kv_chains, geometry,
                        # idle clock) from the same readiness probe
                        self.cluster.observe(rep, payload)
                except Exception:
                    pass
        self._m_ready.set(ready_count)
        if self.cluster is not None:
            self.cluster.autoscale_tick()
        # stream stall watchdog + TTFT hedging
        with self._lock:
            tickets = list(self._tickets)
        for t in tickets:
            if t.done:
                with self._lock:
                    self._tickets.discard(t)
                continue
            stalled = t.stalled_s(now)
            if self.stall_s is not None and stalled > self.stall_s:
                with t._cond:
                    srcs = list(t._srcs)
                for s in srcs:
                    self._migrate_stream(s, why="stalled")
                continue
            if (self.hedge_ms is not None and self.cluster is None
                    and not t.hedged and t.t_first is None
                    and (now - t.t_submit) * 1e3 > self.hedge_ms):
                # hedging is fenced off in pool mode: the duplicate
                # carries the FULL spec, which would re-mix prefill
                # work into decode batches
                self._hedge(t)

    def _hedge(self, ticket: RouterTicket):
        """Single TTFT hedge: one duplicate on a different replica;
        whichever source delivers the first chunk becomes primary and
        the other is cancelled (``RouterTicket._deliver``)."""
        with ticket._cond:
            if ticket.done or ticket._primary is not None \
                    or len(ticket._srcs) != 1:
                return
            current = ticket._srcs[0]
        rep = self._pick(exclude=(current.replica,))
        if rep is None or rep is current.replica:
            return
        ticket.hedged = True
        self._m_hedges.inc()
        if _TRACER.enabled:
            _TRACER.instant("router.hedge", "router",
                            parent=ticket.spec.trace, replica=rep.name)
        stream = rep.prepare(ticket.spec, self._on_chunk,
                             self._on_done, self._on_broken)
        stream._ticket = ticket
        ticket._attach(stream, primary=False)
        try:
            rep.launch(stream)
        except Exception:
            stream.cancel()
            ticket._detach(stream)  # the primary is still in flight

    def _monitor_loop(self):
        while not self._stop.is_set():
            try:
                self._sweep()
            except Exception:
                # the supervisor must outlive anything one sweep hits;
                # a single replica's probe blowing up cannot stop crash
                # detection for the rest
                pass
            self._stop.wait(self.heartbeat_s)
