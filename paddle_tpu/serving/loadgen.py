"""SLO load generation for the serving front-end (ISSUE 12).

Two arrival disciplines drive :class:`ServingFrontend` directly (the
HTTP layer adds parsing cost, not scheduling behavior — the API tests
cover it; the SLO gates measure the scheduler):

* **Open loop** — Poisson arrivals at a target QPS, submitted on wall
  deadlines regardless of completions (the discipline that exposes
  queueing collapse: a closed loop self-throttles and hides it).
* **Closed loop** — fixed concurrency, next request on completion
  (steady-state throughput at a given parallelism).

Latency is measured HOST-SIDE per ticket (submit→first-chunk TTFT,
decode-tail TPOT) — the same quantities the engine's tenant-labeled
Prometheus histograms record, but exact per-request rather than
bucketed, so p99s are sharp at bench sample sizes.

``bench_slo`` (bench.py's ``slo_*``/``multistep_*`` keys) gates:

* multi-step speedup: pure-decode tokens/s at ``multi_step=4`` must be
  ≥ 1.2x ``multi_step=1`` (the ISSUE 12 perf criterion) — measured on
  a host-overhead-dominated geometry (tiny chains) where hiding the
  round trip is the whole game;
* open-loop SLO: p99 TTFT and p99 TPOT under configured budgets at the
  target QPS;
* tenant fairness: the interactive tenant's p99 TTFT under a batch-
  tenant flood must stay < 2x its unloaded p99 (weighted fair queue +
  concurrency shares doing their job).
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

import numpy as np

from .frontend import ServingFrontend

__all__ = ["run_open_loop", "run_closed_loop", "bench_slo_serving",
           "bench_failover_serving", "bench_trace_serving",
           "bench_cluster_serving"]


def _percentile(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    return float(np.percentile(np.asarray(xs), q))


def _lat_stats(tickets) -> Dict[str, float]:
    ttft = [t.ttft_s for t in tickets if t.ttft_s is not None]
    tpot = [t.tpot_s for t in tickets if t.tpot_s is not None]
    toks = sum(len(t.tokens) for t in tickets)
    return {
        "requests": len(tickets),
        "completed": sum(1 for t in tickets
                         if t.done and not t.failure_reason),
        "tokens": toks,
        "ttft_p50_ms": 1e3 * _percentile(ttft, 50),
        "ttft_p99_ms": 1e3 * _percentile(ttft, 99),
        "tpot_p50_ms": 1e3 * _percentile(tpot, 50),
        "tpot_p99_ms": 1e3 * _percentile(tpot, 99),
    }


def _mk_prompt(rng, vocab: int, lo: int, hi: int):
    return rng.integers(0, vocab, (int(rng.integers(lo, hi)),))


def run_open_loop(frontend: ServingFrontend, qps: float, n_requests: int,
                  vocab: int, prompt_range=(16, 48), budget: int = 8,
                  tenant: Optional[str] = None, temperature: float = 0.0,
                  seed: int = 0, timeout_s: float = 300.0) -> Dict:
    """Poisson arrivals at ``qps``; submission times are wall-clock
    deadlines (open loop — no self-throttling). Returns latency stats
    over the completed run plus the QPS actually sustained."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / qps, size=n_requests)
    tickets = []
    t0 = time.perf_counter()
    next_at = t0
    for i in range(n_requests):
        next_at += gaps[i]
        delay = next_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        tickets.append(frontend.submit(
            _mk_prompt(rng, vocab, *prompt_range), budget,
            temperature=temperature, seed=seed + i, tenant=tenant))
    for t in tickets:
        t.result(timeout=timeout_s)
    wall = time.perf_counter() - t0
    out = _lat_stats(tickets)
    out["offered_qps"] = qps
    out["sustained_qps"] = n_requests / wall if wall else 0.0
    out["wall_s"] = wall
    return out


def run_closed_loop(frontend: ServingFrontend, concurrency: int,
                    n_requests: int, vocab: int, prompt_range=(16, 48),
                    budget: int = 8, tenant: Optional[str] = None,
                    seed: int = 0, timeout_s: float = 300.0) -> Dict:
    """Fixed-concurrency closed loop: ``concurrency`` streams in
    flight, each completion immediately replaced."""
    rng = np.random.default_rng(seed)
    tickets = []
    live: List = []
    submitted = 0
    t0 = time.perf_counter()
    while submitted < n_requests or live:
        while submitted < n_requests and len(live) < concurrency:
            t = frontend.submit(_mk_prompt(rng, vocab, *prompt_range),
                                budget, seed=seed + submitted,
                                tenant=tenant)
            tickets.append(t)
            live.append(t)
            submitted += 1
        live[0].result(timeout=timeout_s)
        live = [t for t in live if not t.done]
    wall = time.perf_counter() - t0
    out = _lat_stats(tickets)
    out["concurrency"] = concurrency
    out["tokens_per_sec"] = out["tokens"] / wall if wall else 0.0
    out["wall_s"] = wall
    return out


# ------------------------------------------------------------------ bench
def _precompile(eng, seq_buckets, sampling: bool = False):
    """Compile the engine's whole reachable program lattice up front:
    every (active-slot pow2 bucket, chain-depth pow2) decode program —
    including the depths the chain-depth calibration PROBE can pick
    mid-serve — and every prompt-length prefill bucket the workload
    will hit. Dummy dispatches write only to the trash page (zero
    tables/lengths), so pool state is untouched. This is what makes
    the SLO windows compile-stall-free by construction instead of by
    hoping a warm workload wandered through every shape."""
    import jax
    import jax.numpy as jnp

    from ..inference.engine import _pow2ceil

    nb_full = _pow2ceil(eng.max_slots)
    nbs = sorted({1 << i for i in range(nb_full.bit_length())
                  if (1 << i) <= nb_full})
    ks = sorted({1 << i for i in range(eng.max_chain.bit_length())
                 if (1 << i) <= eng.max_chain})
    zeros = np.zeros
    for nb in nbs:
        tables = jnp.asarray(zeros((nb, eng.max_pages_per_seq), np.int32))
        lengths = jnp.asarray(zeros((nb,), np.int32))
        last = jnp.asarray(zeros((nb,), np.int32))
        temps = jnp.asarray(zeros((nb,), np.float32))
        keys = jnp.asarray(zeros((nb, 2), np.uint32))
        for k in ks:
            decode = eng._get_decode(nb, k, sampling)
            toks, pages, _, _, bad = decode(
                eng._params, eng._pages_flat(), tables, lengths, last,
                temps, keys)
            eng._set_pages(pages)
            jax.device_get(bad)
    for seq in seq_buckets:
        prefill = eng._get_prefill((nb_full, seq), sampling, False)
        ids = jnp.asarray(zeros((nb_full, seq), np.int32))
        valid = jnp.asarray(np.ones((nb_full,), np.int32))
        tables = jnp.asarray(zeros((nb_full, eng.max_pages_per_seq),
                                   np.int32))
        lengths = jnp.asarray(zeros((nb_full,), np.int32))
        temps = jnp.asarray(zeros((nb_full,), np.float32))
        keys = jnp.asarray(zeros((nb_full, 2), np.uint32))
        tok, _, bad, pages = prefill(eng._params, eng._pages_flat(), ids,
                                     valid, tables, lengths, temps, keys)
        eng._set_pages(pages)
        jax.device_get(bad)


def _decode_rate(eng, prompts, budget: int) -> float:
    """Steady-state pure-decode tokens/s: admit everything, then time
    the decode phase alone (the multi-step fast path's regime)."""
    reqs = [eng.add_request(p, budget) for p in prompts]
    eng._admit()  # prefill outside the timed window (r3 protocol)
    done0 = sum(len(r.tokens) for r in reqs)
    t0 = time.perf_counter()
    while eng.step():
        pass
    dt = time.perf_counter() - t0
    return (sum(len(r.tokens) for r in reqs) - done0) / dt


def bench_slo_serving(cfg, on_tpu: bool) -> Dict:
    """The ISSUE 12 acceptance block; see module docstring."""
    from ..inference.engine import Engine
    from ..models.gpt import GPTForCausalLM
    from ..observability import histogram_summary

    model = GPTForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    vocab = cfg.vocab_size
    out: Dict = {}

    # -- multi-step perf gate: host-overhead-dominated decode geometry --
    # (tiny chains: every iteration is a host round trip at N=1, so the
    # fast path's one-fetch-per-N is the dominant saving)
    mslots = 8
    budget = 64 if on_tpu else 32
    rng = np.random.default_rng(5)
    mprompts = [rng.integers(0, vocab, (int(rng.integers(12, 24)),))
                for _ in range(mslots)]

    def multistep_engine(n):
        # chunk_size 1 on the CPU smoke host: the shortest possible
        # chain maximizes the host-overhead fraction per iteration —
        # the regime a tunneled TPU is ALWAYS in (50-100 ms dispatch
        # RTT vs ~20 ms compute), recreated on a host where dispatch
        # is cheap but packing/fetch/harvest are not
        return Engine(model, max_slots=mslots,
                      num_pages=(mslots + 2) * cfg.max_position // 16 + 1,
                      page_size=16, chunk_size=8 if on_tpu else 1,
                      max_chain=1, multi_step=n)

    engines = {}
    for n in (1, 4):
        engines[n] = multistep_engine(n)
        for _ in range(2):  # warm every compiled bucket + depth
            [engines[n].add_request(p, budget) for p in mprompts]
            engines[n].run()
    # INTERLEAVED rep pairs, median of per-pair ratios: back-to-back
    # N=1/N=4 samples share whatever transient load the host has (the
    # CPU smoke box is a single core), so the ratio is stable where
    # sequential medians are not
    pairs = [(_decode_rate(engines[1], mprompts, budget),
              _decode_rate(engines[4], mprompts, budget))
             for _ in range(5)]
    rates = {1: sorted(p[0] for p in pairs)[2],
             4: sorted(p[1] for p in pairs)[2]}
    speedup = sorted(r4 / r1 for r1, r4 in pairs)[2]
    spr = histogram_summary("paddle_tpu_engine_steps_per_roundtrip")
    out.update({
        "slo_multistep1_decode_tokens_per_sec": round(rates[1], 1),
        "slo_multistep4_decode_tokens_per_sec": round(rates[4], 1),
        "multistep_speedup": round(speedup, 3),
        "multistep_speedup_ok": bool(speedup >= 1.2),
        "multistep_max_steps_per_roundtrip": spr.get("max", 0.0),
    })

    # -- open-loop SLO gate ---------------------------------------------
    # target QPS + budgets sized so a healthy scheduler passes with wide
    # margin on the CPU smoke host; on TPU the same shape scales up.
    slots = 8 if on_tpu else 4
    qps = 40.0 if on_tpu else 6.0
    n_req = 200 if on_tpu else 24
    ttft_budget_ms = 500.0 if on_tpu else 1500.0
    tpot_budget_ms = 50.0 if on_tpu else 300.0
    budget = 8 if on_tpu else 4

    eng = Engine(model, max_slots=slots,
                 num_pages=(slots + 2) * cfg.max_position // 16 + 1,
                 page_size=16, chunk_size=8 if on_tpu else 2,
                 max_chain=2, multi_step=4)
    # compile-stall-free measured window: the full program lattice plus
    # one admission wave (the non-program host surfaces)
    _precompile(eng, seq_buckets=(16, 32))
    r = np.random.default_rng(1)
    [eng.add_request(_mk_prompt(r, vocab, 12, 32), budget)
     for _ in range(slots)]
    eng.run()
    fe = ServingFrontend(eng).start()
    ol = run_open_loop(fe, qps=qps, n_requests=n_req, vocab=vocab,
                       prompt_range=(12, 32), budget=budget, seed=9)
    fe.shutdown()
    slo_ok = (ol["ttft_p99_ms"] <= ttft_budget_ms
              and ol["tpot_p99_ms"] <= tpot_budget_ms
              and ol["sustained_qps"] >= 0.8 * qps)
    out.update({
        "slo_qps_target": qps,
        "slo_qps_sustained": round(ol["sustained_qps"], 2),
        "slo_p99_ttft_ms": round(ol["ttft_p99_ms"], 1),
        "slo_p99_tpot_ms": round(ol["tpot_p99_ms"], 1),
        "slo_ttft_budget_ms": ttft_budget_ms,
        "slo_tpot_budget_ms": tpot_budget_ms,
        "slo_ok": bool(slo_ok),
    })

    # -- tenant fairness gate -------------------------------------------
    weights = {"interactive": 8.0, "batch": 1.0}
    i_qps = 10.0 if on_tpu else 3.0
    n_int = 60 if on_tpu else 12
    batch_budget = 128 if on_tpu else 48

    def fairness_run(flood: bool) -> Dict:
        eng = Engine(model, max_slots=slots,
                     num_pages=(2 * slots + 4) * cfg.max_position // 16
                     + 1,
                     page_size=16, chunk_size=8 if on_tpu else 2,
                     max_chain=2, multi_step=4)
        # warm before the measured window (direct engine access — the
        # frontend thread is not running yet): the full program lattice
        # + both tenants' prompt buckets + one mixed admission wave
        _precompile(eng, seq_buckets=(16, 64))
        wr = np.random.default_rng(3)
        [eng.add_request(_mk_prompt(wr, vocab, lo, hi), 4)
         for lo, hi in ((48, 64), (9, 16))]
        eng.run()
        fe = ServingFrontend(eng, tenant_weights=weights).start()
        batch_tickets = []
        if flood:
            r = np.random.default_rng(13)
            for i in range(4 * slots):
                batch_tickets.append(fe.submit(
                    _mk_prompt(r, vocab, 48, 64), batch_budget,
                    tenant="batch", seed=100 + i))
        stats = run_open_loop(fe, qps=i_qps, n_requests=n_int,
                              vocab=vocab, prompt_range=(9, 16),
                              budget=4, tenant="interactive", seed=17)
        for t in batch_tickets:
            t.result(timeout=600.0)
        fe.shutdown()
        return stats

    alone = fairness_run(flood=False)
    flooded = fairness_run(flood=True)
    # the degrade baseline carries a scheduler-jitter floor: an unloaded
    # p99 of ~10 ms is OS-scheduling noise on the single-core smoke
    # host (p99 over a small sample IS the max sample), and dividing by
    # noise makes the gate a coin flip. The floor is a couple of
    # engine-step quanta — below it, "degradation" is not queueing.
    floor_ms = 20.0 if on_tpu else 50.0
    baseline = max(alone["ttft_p99_ms"], floor_ms)
    degrade = (flooded["ttft_p99_ms"] / baseline if baseline else 0.0)
    out.update({
        "fairness_interactive_p99_ttft_ms_alone":
            round(alone["ttft_p99_ms"], 1),
        "fairness_interactive_p99_ttft_ms_flooded":
            round(flooded["ttft_p99_ms"], 1),
        "fairness_baseline_floor_ms": floor_ms,
        "fairness_ttft_degrade": round(degrade, 3),
        "fairness_ok": bool(0.0 < degrade < 2.0),
    })
    return out


# -------------------------------------------------------------- tracing
def bench_trace_serving(cfg, on_tpu: bool) -> Dict:
    """bench.py ``bench_trace`` block (ISSUE 18 satellite): the span
    recorder's steady-state cost as an interleaved-rep ratio of median
    scheduling-step times, tracing ``on`` vs ``off``, on the bench_slo
    engine geometry (multi-step decode chains + mixed chunk steps, the
    surfaces the tentpole instrumented). Per-mode medians are floored
    at the host jitter floor (50 ms on the single-core CPU smoke host,
    20 ms on TPU) before the ratio; the gate is ``trace_overhead_frac``
    (median-on / median-off - 1) < 2% with > 0 spans recorded."""
    from ..inference.engine import Engine
    from ..models.gpt import GPTForCausalLM
    from ..observability import metric_total
    from ..observability.tracing import TRACER, configure_tracing

    model = GPTForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    vocab = cfg.vocab_size
    slots = 4
    eng = Engine(model, max_slots=slots,
                 num_pages=(slots + 2) * cfg.max_position // 16 + 1,
                 page_size=16, chunk_size=8 if on_tpu else 2,
                 max_chain=2, multi_step=4)
    rng = np.random.default_rng(21)

    def workload():
        return [eng.add_request(_mk_prompt(rng, vocab, 12, 32), 8)
                for _ in range(slots)]

    spans0 = metric_total("paddle_tpu_trace_spans_total")
    # warmup under BOTH modes: compile every program, touch both record
    # paths once (the enabled-guard branch and the ring append)
    for mode in ("on", "off"):
        configure_tracing(mode, process="bench")
        workload()
        eng.run()
    # INTERLEAVED (off, on) rep pairs: back-to-back samples share the
    # host's transient load (single-core smoke box), so the ratio is
    # stable where sequential medians are not
    reps, steps = 4, {"off": [], "on": []}
    try:
        for _ in range(reps):
            for mode in ("off", "on"):
                configure_tracing(mode, process="bench")
                workload()
                while True:
                    t0 = time.perf_counter()
                    live = eng.step()
                    steps[mode].append(time.perf_counter() - t0)
                    if not live:
                        break
    finally:
        configure_tracing("off")
        TRACER.clear()
    floor_s = 0.020 if on_tpu else 0.050
    med_off = float(np.median(steps["off"]))
    med_on = float(np.median(steps["on"]))
    ratio = max(med_on, floor_s) / max(med_off, floor_s)
    overhead = max(0.0, ratio - 1.0)
    spans = int(metric_total("paddle_tpu_trace_spans_total") - spans0)
    ok = overhead < 0.02 and spans > 0
    if not ok:
        print(f"WARNING: bench_trace gate failed: overhead="
              f"{overhead:.4f} (<0.02 required), spans={spans} (>0)")
    return {
        "trace_overhead_frac": round(overhead, 4),
        "trace_step_ms_off": round(1e3 * med_off, 3),
        "trace_step_ms_on": round(1e3 * med_on, 3),
        "trace_jitter_floor_ms": 1e3 * floor_s,
        "trace_bench_spans": spans,
        "trace_ok": bool(ok),
    }


# ------------------------------------------------------------ ownership
def bench_ownership_serving(cfg, on_tpu: bool) -> Dict:
    """bench.py ``bench_ownership`` block (ISSUE 19 satellite): the
    runtime ownership guard's steady-state cost as an interleaved-rep
    ratio of median scheduling-step times, guard ARMED vs disarmed, on
    a guarded TIERED engine (Engine + CacheCoordinator + PrefixCache +
    HostTier all ``guard_engine``-wrapped, so every hot-path attribute
    write — slot state, counters, tier bookkeeping — pays the
    ``__setattr__`` interception). Same harness as ``bench_trace``:
    per-mode medians floored at the host jitter floor (50 ms CPU smoke
    host / 20 ms TPU) before the ratio; the gate is
    ``ownership_guard_overhead_frac`` < 2%. An OwnershipError anywhere
    in the run would propagate out of the block (the wrapper surfaces
    it as a bench error), so a finishing run doubles as the clean-tree
    runtime proof at bench geometry."""
    from ..analysis import guard_engine, ownership_guard
    from ..inference.engine import Engine
    from ..models.gpt import GPTForCausalLM

    model = GPTForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    vocab = cfg.vocab_size
    slots = 4
    eng = Engine(model, max_slots=slots,
                 num_pages=(slots + 2) * cfg.max_position // 16 + 1,
                 page_size=16, chunk_size=8 if on_tpu else 2,
                 max_chain=2, multi_step=4,
                 prefix_cache=True, kv_host_pages=64)
    guard_engine(eng)
    rng = np.random.default_rng(29)
    # templated prompts: repeats hit the prefix cache and churn the
    # spill tier, so the guarded HostTier/worker hand-off is ON the
    # measured path, not idle
    tpls = [rng.integers(0, vocab, (24,)) for _ in range(3)]

    def workload():
        return [eng.add_request(
            np.concatenate([tpls[i % 3],
                            rng.integers(0, vocab, (5,))]), 8)
                for i in range(slots)]

    def run_mode(armed, record=None):
        with ownership_guard(enabled=True) if armed else \
                contextlib.nullcontext():
            workload()
            while True:
                t0 = time.perf_counter()
                live = eng.step()
                if record is not None:
                    record.append(time.perf_counter() - t0)
                if not live:
                    return

    try:
        # warmup under BOTH modes: compile every program, touch the
        # armed branch of every guarded __setattr__ once
        run_mode(False)
        run_mode(True)
        # INTERLEAVED (off, on) rep pairs, as in bench_trace: paired
        # samples share the smoke host's transient load
        reps, steps = 4, {"off": [], "on": []}
        for _ in range(reps):
            run_mode(False, steps["off"])
            run_mode(True, steps["on"])
    finally:
        eng._cache.shutdown_tier()
    floor_s = 0.020 if on_tpu else 0.050
    med_off = float(np.median(steps["off"]))
    med_on = float(np.median(steps["on"]))
    ratio = max(med_on, floor_s) / max(med_off, floor_s)
    overhead = max(0.0, ratio - 1.0)
    ok = overhead < 0.02
    if not ok:
        print(f"WARNING: bench_ownership gate failed: overhead="
              f"{overhead:.4f} (<0.02 required)")
    return {
        "ownership_guard_overhead_frac": round(overhead, 4),
        "ownership_step_ms_off": round(1e3 * med_off, 3),
        "ownership_step_ms_on": round(1e3 * med_on, 3),
        "ownership_jitter_floor_ms": 1e3 * floor_s,
        "ownership_ok": bool(ok),
    }


# ------------------------------------------------------------- failover
def bench_failover_serving(cfg, on_tpu: bool) -> Dict:
    """The ISSUE 13 acceptance block: open-loop load over a 2-replica
    router with one injected replica kill mid-window. Gates:

    * every request completes (zero ``request_failures_total`` growth —
      the killed replica's streams migrate, they don't die);
    * p99 TTFT of UNAFFECTED requests (never migrated) degrades < 2x vs
      a no-kill baseline, measured as interleaved (baseline, kill) rep
      pairs with a jitter floor — the single-core smoke host's p99 over
      a small sample IS the max sample, and one cold compile is ~1 s of
      p99 (BASELINE notes), so replicas are pre-warmed and restarts
      draw from a pre-warmed standby pool.

    ``paddle_tpu_router_migrations_total`` / ``replica_restarts_total``
    land in bench.py's metrics block from this run.
    """
    from collections import deque

    from ..inference.engine import Engine
    from ..models.gpt import GPTForCausalLM
    from ..observability import metric_total
    from .replica import InProcReplica
    from .router import Router

    model = GPTForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    vocab = cfg.vocab_size
    slots = 4
    qps = 20.0 if on_tpu else 6.0
    n_req = 60 if on_tpu else 16
    budget = 16
    pairs = 3

    def warm_frontend():
        # the slow-step fault pins decode at ~15 ms/step so streams are
        # seconds long — the kill provably lands on a replica with work
        # in flight (without it the CPU smoke drains each 24-token
        # stream in ~20 ms and the "mid-stream" kill hits an idle box)
        eng = Engine(model, max_slots=slots,
                     num_pages=(slots + 2) * cfg.max_position // 16 + 1,
                     page_size=16, chunk_size=1, max_chain=1,
                     multi_step=1,
                     fault_plan="slow-step:every=1,delay_ms=12")
        _precompile(eng, seq_buckets=(16, 32))
        r = np.random.default_rng(11)
        [eng.add_request(_mk_prompt(r, vocab, 12, 32), 2)
         for _ in range(2)]
        eng.run()
        return ServingFrontend(eng)

    # pre-warmed standby pool: one per replica + one per planned
    # restart, so a mid-window restart swaps in a warm engine instead
    # of spending the measured window compiling (single-core host)
    standby: deque = deque(warm_frontend() for _ in range(2 + pairs))
    factory = (lambda: standby.popleft() if standby
               else warm_frontend())

    reps = [InProcReplica(factory, name=f"bench-r{i}", index=i)
            for i in range(2)]
    router = Router(reps, heartbeat_s=0.05, stall_s=None,
                    restart_dead=True, restart_backoff_s=0.05)
    router.start()

    def one_run(kill: bool, seed: int) -> Dict:
        rng = np.random.default_rng(seed)
        tickets = []
        gaps = rng.exponential(1.0 / qps, size=n_req)
        t0 = time.perf_counter()
        if kill:
            def killer():
                # one injected replica kill mid-window: past a third of
                # the window AND the victim provably has work in flight
                deadline = t0 + 0.8 * n_req / qps
                victim = max(reps, key=lambda r: r.inflight)
                while time.perf_counter() < deadline:
                    victim = max(reps, key=lambda r: r.inflight)
                    if victim.inflight >= 1 and time.perf_counter() \
                            >= t0 + 0.3 * n_req / qps:
                        break
                    time.sleep(0.02)
                victim.kill()

            import threading

            threading.Thread(target=killer, daemon=True).start()
        next_at = t0
        for i in range(n_req):
            next_at += gaps[i]
            delay = next_at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            tickets.append(router.submit(
                _mk_prompt(rng, vocab, 12, 32), budget, seed=seed + i))
        for t in tickets:
            t.result(timeout=300.0)
        if kill:
            # wait out the supervised restart so the next rep pair
            # starts from two live replicas again
            deadline = time.perf_counter() + 120.0
            while time.perf_counter() < deadline:
                if all(r.alive() for r in reps):
                    break
                time.sleep(0.1)
        unaffected = [t for t in tickets if t.migrations == 0]
        ttft = [t.ttft_s for t in unaffected if t.ttft_s is not None]
        return {
            "completed": sum(1 for t in tickets
                             if t.done and not t.failure_reason),
            "requests": len(tickets),
            "migrated": sum(1 for t in tickets if t.migrations),
            "p99_ttft_ms": 1e3 * _percentile(ttft, 99),
        }

    fail0 = metric_total("paddle_tpu_request_failures_total")
    # interleaved rep pairs (single-core host): each (baseline, kill)
    # pair shares the host's transient load; the gate is the MEDIAN of
    # per-pair ratios over a jitter floor
    floor_ms = 20.0 if on_tpu else 50.0
    runs = []
    for p in range(pairs):
        base = one_run(kill=False, seed=100 + 10 * p)
        killed = one_run(kill=True, seed=500 + 10 * p)
        runs.append((base, killed))
    ratios = sorted(
        k["p99_ttft_ms"] / max(b["p99_ttft_ms"], floor_ms)
        for b, k in runs)
    degrade = ratios[pairs // 2]
    completed = sum(k["completed"] for _, k in runs)
    requests = sum(k["requests"] for _, k in runs)
    migrated = sum(k["migrated"] for _, k in runs)
    router.shutdown()
    out = {
        "failover_requests_per_run": n_req,
        "failover_qps": qps,
        "failover_baseline_p99_ttft_ms": round(
            sorted(b["p99_ttft_ms"] for b, _ in runs)[pairs // 2], 1),
        "failover_killed_p99_ttft_ms": round(
            sorted(k["p99_ttft_ms"] for _, k in runs)[pairs // 2], 1),
        "failover_ttft_floor_ms": floor_ms,
        "failover_ttft_degrade": round(degrade, 3),
        "failover_migrated_streams": migrated,
        "failover_completed": completed,
        "failover_zero_failures": bool(
            completed == requests
            and metric_total("paddle_tpu_request_failures_total")
            == fail0),
        "failover_migrations_total": int(
            metric_total("paddle_tpu_router_migrations_total")),
        "failover_replica_restarts_total": int(
            metric_total("paddle_tpu_replica_restarts_total")),
        "failover_ok": bool(degrade < 2.0 and completed == requests
                            and migrated >= 1),
    }
    return out


def bench_cluster_serving(cfg, on_tpu: bool) -> Dict:
    """The ISSUE 20 acceptance block: a shared-prefix multi-tenant
    workload over a 3-replica prefill/decode cluster. Gates:

    * **zero stream failures** — every request completes on both the
      pooled fleet and the unpooled baseline;
    * **hit rate within 1.2x of the single-giant-cache oracle** — the
      fleet's aggregate prefix-cache hit rate (prefill pool warm per
      tenant, decode pool warmed by handoff adoption + cache-aware
      placement) must not fall more than 1.2x below ONE engine holding
      every tenant's prefix in one cache;
    * **mixed p99 TTFT < 2x the unpooled baseline** over the jitter
      floor — disaggregation (prefill leg + handoff + decode leg) must
      not tax time-to-first-token, which the prefill pool serves
      directly.

    ``paddle_tpu_cluster_{handoffs,handoff_bytes,fallbacks}_total``
    land in bench.py's metrics block from this run.
    """
    from ..inference.engine import Engine
    from ..models.gpt import GPTForCausalLM
    from ..observability import metric_total
    from .replica import InProcReplica
    from .router import Router

    model = GPTForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    vocab = cfg.vocab_size
    slots = 4
    page = 16
    qps = 20.0 if on_tpu else 6.0
    n_req = 64 if on_tpu else 24
    budget = 8
    tenants = 4
    rng0 = np.random.default_rng(7)
    # one fixed 2-page prefix per tenant: the shareable unit every
    # placement/caching claim below is about
    prefixes = [_mk_prompt(rng0, vocab, 2 * page, 2 * page + 1)
                for _ in range(tenants)]

    def warm_engine(num_pages):
        eng = Engine(model, max_slots=slots, num_pages=num_pages,
                     page_size=page, chunk_size=1, max_chain=1,
                     prefix_cache=True)
        _precompile(eng, seq_buckets=(64,))
        return eng

    fleet_pages = (slots + 2) * cfg.max_position // page + 1

    def hit_rate_delta(h0, m0):
        dh = metric_total("paddle_tpu_prefix_cache_hits_total") - h0
        dm = metric_total("paddle_tpu_prefix_cache_misses_total") - m0
        return dh / (dh + dm) if (dh + dm) else 0.0

    def workload(submit, seed):
        rng = np.random.default_rng(seed)
        gaps = rng.exponential(1.0 / qps, size=n_req)
        tickets = []
        next_at = time.perf_counter()
        for i in range(n_req):
            next_at += gaps[i]
            delay = next_at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            prompt = np.concatenate([prefixes[i % tenants],
                                     _mk_prompt(rng, vocab, 8, 17)])
            tickets.append(submit(prompt, budget,
                                  tenant=f"t{i % tenants}"))
        for t in tickets:
            t.result(timeout=300.0)
        ttft = [t.ttft_s for t in tickets if t.ttft_s is not None]
        return {
            "completed": sum(1 for t in tickets
                             if t.done and not t.failure_reason),
            "requests": len(tickets),
            "p99_ttft_ms": 1e3 * _percentile(ttft, 99),
        }

    fail0 = metric_total("paddle_tpu_request_failures_total")

    # --- unpooled baseline: same 3 engines, every replica does both
    base_reps = [InProcReplica(
        lambda: ServingFrontend(warm_engine(fleet_pages)),
        name=f"base-r{i}", index=i) for i in range(3)]
    base_router = Router(base_reps, heartbeat_s=0.05,
                         stall_s=None).start()
    base = workload(base_router.submit, seed=100)
    base_router.shutdown()

    # --- pooled cluster: 1 prefill + 2 decode, KV handoff between
    reps = [InProcReplica(
        lambda: ServingFrontend(warm_engine(fleet_pages)),
        name=f"cluster-r{i}", index=i) for i in range(3)]
    router = Router(reps, heartbeat_s=0.05, stall_s=None,
                    pools={"prefill": 1, "decode": 2}).start()
    deadline = time.perf_counter() + 30.0
    while router.cluster._page_size is None \
            and time.perf_counter() < deadline:
        time.sleep(0.02)  # one sweep feeds geometry into the view
    h0 = metric_total("paddle_tpu_prefix_cache_hits_total")
    m0 = metric_total("paddle_tpu_prefix_cache_misses_total")
    ho0 = metric_total("paddle_tpu_cluster_handoffs_total")
    hb0 = metric_total("paddle_tpu_cluster_handoff_bytes_total")
    fb0 = metric_total("paddle_tpu_cluster_fallbacks_total")
    pooled = workload(router.submit, seed=200)
    pooled_rate = hit_rate_delta(h0, m0)
    handoffs = metric_total("paddle_tpu_cluster_handoffs_total") - ho0
    handoff_mb = (metric_total("paddle_tpu_cluster_handoff_bytes_total")
                  - hb0) / 2 ** 20
    fallbacks = metric_total("paddle_tpu_cluster_fallbacks_total") - fb0
    router.shutdown()

    # --- oracle: ONE engine whose cache could hold the whole fleet's
    # prefixes — the upper bound cluster hit rate is judged against
    oracle_fe = ServingFrontend(warm_engine(4 * fleet_pages)).start()
    h0 = metric_total("paddle_tpu_prefix_cache_hits_total")
    m0 = metric_total("paddle_tpu_prefix_cache_misses_total")
    oracle = workload(oracle_fe.submit, seed=300)
    oracle_rate = hit_rate_delta(h0, m0)
    oracle_fe.shutdown()

    floor_ms = 20.0 if on_tpu else 50.0
    degrade = (pooled["p99_ttft_ms"]
               / max(base["p99_ttft_ms"], floor_ms))
    completed = (base["completed"] + pooled["completed"]
                 + oracle["completed"])
    requests = (base["requests"] + pooled["requests"]
                + oracle["requests"])
    zero_failures = bool(
        completed == requests
        and metric_total("paddle_tpu_request_failures_total") == fail0)
    hit_ok = bool(pooled_rate * 1.2 >= oracle_rate)
    out = {
        "cluster_requests_per_run": n_req,
        "cluster_tenants": tenants,
        "cluster_qps": qps,
        "cluster_hit_rate": round(pooled_rate, 3),
        "cluster_oracle_hit_rate": round(oracle_rate, 3),
        "cluster_hit_rate_ok": hit_ok,
        "cluster_p99_ttft_ms": round(pooled["p99_ttft_ms"], 1),
        "cluster_baseline_p99_ttft_ms": round(base["p99_ttft_ms"], 1),
        "cluster_ttft_floor_ms": floor_ms,
        "cluster_ttft_degrade": round(degrade, 3),
        "cluster_handoffs": int(handoffs),
        "cluster_handoff_mb": round(handoff_mb, 3),
        "cluster_fallbacks": int(fallbacks),
        "cluster_zero_failures": zero_failures,
        "cluster_ok": bool(hit_ok and degrade < 2.0 and zero_failures),
    }
    if not out["cluster_ok"]:
        print(f"WARNING: cluster serving gate failed: hit_rate="
              f"{pooled_rate:.3f} vs oracle {oracle_rate:.3f} (1.2x), "
              f"ttft_degrade={degrade:.3f} (<2.0), "
              f"zero_failures={zero_failures}")
    return out
