"""Supervised engine replicas for the multi-replica serving layer
(ISSUE 13 tentpole).

A *replica* is one serving engine behind a uniform lifecycle + stream
surface the :mod:`router <paddle_tpu.serving.router>` can supervise:

* :class:`InProcReplica` — an ``Engine`` + ``ServingFrontend`` pair in
  this process (built by a caller-supplied factory so a restart gets a
  FRESH engine). Liveness is the frontend's engine thread; sudden death
  is ``ServingFrontend.poison()`` (the ``replica-crash`` fault point's
  in-process arm: the thread vanishes without finishing its tickets,
  exactly like a SIGKILLed process's streams going silent).
* :class:`SubprocessReplica` — a worker process speaking the
  :class:`~paddle_tpu.serving.server.ApiServer` protocol (e.g.
  ``examples/serve_llama_paged.py --api-port 0``). Liveness is the
  process being up; readiness is its ``/readyz``; streams ride SSE on a
  per-stream reader thread; ``kill()`` is a real SIGKILL.

The split health surface both implement (ISSUE 13):

* **liveness** (``alive``) — the process/thread exists. Only a dead
  replica gets restarted.
* **readiness** (``ready()``) — fit for NEW traffic: not draining,
  engine watchdog below its degradation threshold, queue depth in
  bounds. The router health-gates routing on this; a live-but-unready
  replica keeps its in-flight streams and takes no new ones. The
  payload's ``quarantined`` field (ISSUE 14) is the one unreadiness
  that is WORSE than death: the engine's own integrity audit proved
  its weights corrupt, so the router must not merely stop routing new
  streams — it fences the replica (kill) and migrates the in-flight
  ones too, because their future tokens would flow through the same
  corrupt weights. Both replica kinds surface it: the in-process one
  straight from ``ServingFrontend.readiness()``, the subprocess one
  through the ``/readyz`` JSON body (503s still carry the payload).
* **heartbeat** (``heartbeat(plan)``) — the supervisor's periodic
  probe; the ``heartbeat-drop`` fault point (keyed by replica index via
  the plan's ``rid`` selector) makes it report failure while the
  replica stays up, driving the router's false-positive arm.

Every stream callback is invoked from replica-owned threads (engine
thread or SSE reader); the router's handlers do their own locking.
This module has no ``async def`` — all blocking I/O here runs on
dedicated threads, never an event loop (tpulint TPL901 guards that).
"""
from __future__ import annotations

import base64
import http.client
import json
import signal
import subprocess
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Replica", "InProcReplica", "SubprocessReplica",
           "StreamSpec", "ReplicaStream",
           "encode_kv_payload", "decode_kv_payload"]


# --------------------------------------------------- KV handoff codec
def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 etc. register through ml_dtypes (jax ships it)
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def encode_kv_payload(payload: Dict) -> Dict:
    """JSON-encode a KV handoff payload (ISSUE 20): page buffer rows
    become ``{dtype, shape, b64}`` triples so the payload can cross the
    subprocess transport (``/v1/kv``). Digests/dev_sums/tokens are
    already JSON-native."""
    out = dict(payload)
    out["pages"] = [
        [{"dtype": str(a.dtype), "shape": list(a.shape),
          "b64": base64.b64encode(
              np.ascontiguousarray(a).tobytes()).decode("ascii")}
         for a in rows]
        for rows in payload["pages"]]
    return out


def decode_kv_payload(obj: Dict) -> Dict:
    """Inverse of :func:`encode_kv_payload`. The decoded rows are
    read-only views over the b64 bytes — the adopter only hashes and
    stacks them, never writes in place."""
    out = dict(obj)
    out["pages"] = [
        [np.frombuffer(base64.b64decode(d["b64"]),
                       dtype=_np_dtype(d["dtype"])).reshape(d["shape"])
         for d in rows]
        for rows in obj["pages"]]
    return out


class StreamSpec:
    """The replica-agnostic description of one stream: everything needed
    to (re)submit it anywhere, including the resume-from-emitted state."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "seed",
                 "tenant", "deadline_s", "resume_tokens", "trace",
                 "t_origin")

    def __init__(self, prompt: Sequence[int], max_new_tokens: int,
                 temperature: float = 0.0, seed: Optional[int] = None,
                 tenant: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 resume_tokens: Optional[List[int]] = None,
                 trace: Optional[str] = None,
                 t_origin: Optional[float] = None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.seed = seed
        self.tenant = tenant
        self.deadline_s = deadline_s
        self.resume_tokens = list(resume_tokens) if resume_tokens else None
        # request tracing (ISSUE 18): the parent SpanContext wire string
        # and the ORIGINAL submit time (perf_counter, same-process only)
        # — both survive a migration, so the resumed stream lands in the
        # same trace and its TTFT placement component stays honest
        self.trace = trace
        self.t_origin = t_origin


class ReplicaStream:
    """One in-flight stream on one replica. The router owns the
    callbacks; ``cancel()`` tears the upstream down without firing
    ``on_broken`` (a cancelled stream is not a crashed one)."""

    def __init__(self, replica: "Replica", spec: StreamSpec,
                 on_chunk: Callable, on_done: Callable,
                 on_broken: Callable):
        self.replica = replica
        self.spec = spec
        self.on_chunk = on_chunk      # (stream, list[int])
        self.on_done = on_done        # (stream, failure_reason|None)
        self.on_broken = on_broken    # (stream, exc)
        self.cancelled = False
        self.closed = False
        self._impl = None  # replica-specific handle

    def cancel(self):
        self.cancelled = True
        self.replica._cancel(self)


class Replica:
    """Base lifecycle/stream surface; see module docstring."""

    def __init__(self, name: str, index: int = 0):
        self.name = name
        self.index = int(index)
        self.restarts = 0
        self._streams: set = set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------ health
    def alive(self) -> bool:
        raise NotImplementedError

    def ready(self) -> Dict:
        raise NotImplementedError

    def heartbeat(self, plan=None) -> bool:
        """Supervisor probe: False means "treat me as dead". The
        ``heartbeat-drop`` fault point (``rid`` = replica index) forces
        a drop without killing anything — the router must migrate
        anyway and the resumed streams must stay bit-identical."""
        if plan is not None and plan.fire("heartbeat-drop",
                                          rid=self.index):
            return False
        return self._probe()

    def _probe(self) -> bool:
        return self.alive()

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._streams)

    def _track(self, stream: ReplicaStream):
        with self._lock:
            self._streams.add(stream)

    def _untrack(self, stream: ReplicaStream):
        stream.closed = True
        with self._lock:
            self._streams.discard(stream)

    def streams(self) -> List[ReplicaStream]:
        with self._lock:
            return list(self._streams)

    # --------------------------------------------------------- lifecycle
    def start(self):
        raise NotImplementedError

    def kill(self):
        """Sudden death (chaos surface): no drain, no goodbyes."""
        raise NotImplementedError

    def stop(self):
        """Graceful teardown (test/bench cleanup)."""
        raise NotImplementedError

    def restart(self):
        """Replace the dead replica with a fresh one (the supervisor's
        recovery arm); counted by the router's restart metric."""
        raise NotImplementedError

    # ----------------------------------------------------------- streams
    def prepare(self, spec: StreamSpec, on_chunk, on_done,
                on_broken) -> ReplicaStream:
        """Phase 1: build the stream handle WITHOUT starting any flow,
        so the caller can wire it up (attach it to a ticket) before the
        first chunk can possibly arrive. ``launch`` starts the flow."""
        stream = ReplicaStream(self, spec, on_chunk, on_done, on_broken)
        self._track(stream)
        return stream

    def launch(self, stream: ReplicaStream):
        raise NotImplementedError

    def submit(self, spec: StreamSpec, on_chunk, on_done,
               on_broken) -> ReplicaStream:
        """prepare + launch in one call (single-consumer convenience;
        the router uses the two-phase form)."""
        stream = self.prepare(spec, on_chunk, on_done, on_broken)
        self.launch(stream)
        return stream

    def _cancel(self, stream: ReplicaStream):
        raise NotImplementedError

    # ------------------------------------------------ cluster handoff
    # Default = "this replica does not speak the handoff protocol":
    # export yields nothing and import adopts nothing, so a cluster
    # pairing an OLDER replica degrades to resume-from-emitted
    # recompute — the same versioned-payload fallback the readiness
    # kv_chains field rides (ISSUE 20 small fix). Never an error.
    def export_kv(self, tokens: Sequence[int]) -> Optional[Dict]:
        """Capture ``tokens``' cached KV pages into a handoff payload
        (prefill side); None when unsupported or nothing is cached."""
        return None

    def import_kv(self, payload: Dict) -> int:
        """Adopt a shipped payload into this replica's pool (decode
        side); returns pages adopted (0 = caller recomputes)."""
        return 0


class InProcReplica(Replica):
    """An Engine+ServingFrontend replica in this process. ``factory()``
    must return a STARTED :class:`~paddle_tpu.serving.frontend.
    ServingFrontend` (or one this replica may start); restarts call it
    again, so each incarnation gets a fresh engine and page pool."""

    def __init__(self, factory: Callable, name: str = "inproc",
                 index: int = 0):
        super().__init__(name, index)
        self._factory = factory
        self._fe = None

    @property
    def frontend(self):
        return self._fe

    def start(self):
        if self._fe is None:
            self._fe = self._factory()
            self._fe.start()
        return self

    def alive(self) -> bool:
        return self._fe is not None and self._fe.alive

    def ready(self) -> Dict:
        if not self.alive():
            return {"ready": False, "alive": False}
        return self._fe.readiness()

    def kill(self):
        if self._fe is not None:
            self._fe.poison()

    def stop(self):
        if self._fe is not None:
            self._fe.shutdown()

    def restart(self):
        if self._fe is not None:
            # the dead incarnation's KV host tier dies with it
            # (ISSUE 15): its spill worker is queued on a pool the
            # fresh engine will never see, and a quarantined replica's
            # host copies were captured on hardware the restart exists
            # to distrust. The frontend's own loop-exit does this too;
            # a poisoned thread may still be mid-exit, so the
            # supervisor makes it unconditional (idempotent).
            try:
                self._fe.engine._cache.shutdown_tier()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
        self._fe = self._factory()
        self._fe.start()
        self.restarts += 1
        return self

    def launch(self, stream: ReplicaStream):
        spec = stream.spec

        def bridge(chunk):
            if stream.closed:
                return
            if chunk is None:
                ticket = stream._impl
                self._untrack(stream)
                stream.on_done(stream,
                               ticket.failure_reason if ticket else None)
            else:
                stream.on_chunk(stream, chunk)

        stream._impl = self._fe.submit(
            spec.prompt, spec.max_new_tokens,
            temperature=spec.temperature, seed=spec.seed,
            tenant=spec.tenant, deadline_s=spec.deadline_s,
            on_chunk=bridge, resume_tokens=spec.resume_tokens,
            trace=spec.trace, t_origin=spec.t_origin)
        return stream

    def _cancel(self, stream: ReplicaStream):
        self._untrack(stream)
        if stream._impl is not None and self._fe is not None \
                and self._fe.alive:
            self._fe.cancel(stream._impl)

    # ------------------------------------------------ cluster handoff
    def export_kv(self, tokens: Sequence[int]) -> Optional[Dict]:
        """In-process handoff export: the payload is a shared host-slab
        reference (numpy rows), no serialization round trip."""
        if self._fe is None or not self._fe.alive:
            return None
        try:
            return self._fe.export_kv(tokens)
        except Exception:
            return None  # dead/poisoned engine thread: recompute

    def import_kv(self, payload: Dict) -> int:
        if self._fe is None or not self._fe.alive or not payload:
            return 0
        try:
            return int(self._fe.import_kv(payload))
        except Exception:
            return 0


class SubprocessReplica(Replica):
    """A worker process behind the ApiServer HTTP protocol. ``argv`` is
    the worker command line; the worker must print
    ``api: http://HOST:PORT/...`` on stdout once bound (the
    ``serve_llama_paged.py --api-port`` contract)."""

    def __init__(self, argv: Sequence[str], name: str = "worker",
                 index: int = 0, env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None, startup_timeout_s: float = 120.0,
                 probe_timeout_s: float = 2.0):
        super().__init__(name, index)
        self.argv = list(argv)
        self.env = dict(env) if env is not None else None
        self.cwd = cwd
        self.startup_timeout_s = float(startup_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.proc: Optional[subprocess.Popen] = None
        self.host: Optional[str] = None
        self.port: Optional[int] = None

    # --------------------------------------------------------- lifecycle
    def start(self):
        self.proc = subprocess.Popen(
            self.argv, cwd=self.cwd, env=self.env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        bound = threading.Event()

        def pump():
            for line in self.proc.stdout:
                if line.startswith("api: http") and not bound.is_set():
                    # "api: http://127.0.0.1:PORT/v1/completions (...)"
                    hostport = line.split("//", 1)[1].split("/", 1)[0]
                    # tpulint: disable=TPL1501 -- Event-ordered hand-off:
                    # pump publishes once, then bound.set(); every other
                    # thread reads only after bound.wait()
                    self.host, port = hostport.rsplit(":", 1)
                    # tpulint: disable=TPL1501 -- same Event-ordered
                    # hand-off as host above
                    self.port = int(port)
                    bound.set()
            bound.set()  # EOF: unblock the waiter either way

        # keep draining stdout for the worker's lifetime so its prints
        # can never fill the pipe and wedge it
        threading.Thread(target=pump, daemon=True,
                         name=f"replica-{self.name}-stdout").start()
        if not bound.wait(self.startup_timeout_s) or self.port is None:
            raise RuntimeError(
                f"replica {self.name!r} never printed its api endpoint "
                f"(exit={self.proc.poll()})")
        return self

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def _get_json(self, path: str):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.probe_timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()

    def ready(self) -> Dict:
        if not self.alive():
            return {"ready": False, "alive": False}
        try:
            status, payload = self._get_json("/readyz")
        except Exception:
            return {"ready": False, "alive": True}
        payload["ready"] = status == 200
        return payload

    def _probe(self) -> bool:
        if not self.alive():
            return False
        try:
            status, _ = self._get_json("/healthz")
            return status == 200
        except Exception:
            return False

    def kill(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()  # SIGKILL — the chaos gate's real crash

    def stop(self):
        if self.proc is None:
            return
        if self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGTERM)
                self.proc.wait(timeout=60)
            except Exception:
                self.proc.kill()
        try:
            self.proc.wait(timeout=30)
        except Exception:
            pass

    def restart(self):
        self.stop()
        # tpulint: disable=TPL1501 -- the old pump died at stdout EOF in
        # stop(); start() below publishes via a fresh Event-ordered pump
        self.host = self.port = None
        self.start()
        self.restarts += 1
        return self

    # ----------------------------------------------------------- streams
    def launch(self, stream: ReplicaStream):
        spec = stream.spec
        payload = {"prompt": spec.prompt,
                   "max_tokens": spec.max_new_tokens,
                   "temperature": spec.temperature, "stream": True}
        if spec.seed is not None:
            payload["seed"] = int(spec.seed)
        if spec.deadline_s is not None:
            payload["deadline_ms"] = 1e3 * spec.deadline_s
        if spec.resume_tokens:
            payload["resume_tokens"] = list(spec.resume_tokens)
        headers = {"Content-Type": "application/json"}
        if spec.tenant:
            headers["X-Tenant"] = spec.tenant
        if spec.trace:
            # trace context crosses the process boundary as a plain
            # header (ISSUE 18) — the worker's spans join THIS trace
            headers["X-Trace-Context"] = spec.trace
        conn = http.client.HTTPConnection(self.host, self.port)
        stream._impl = conn
        threading.Thread(
            target=self._pump_sse, daemon=True,
            name=f"replica-{self.name}-stream",
            args=(stream, conn, payload, headers)).start()
        return stream

    def _pump_sse(self, stream: ReplicaStream, conn, payload, headers):
        """Per-stream reader thread: forward SSE chunks, classify the
        ending — ``[DONE]`` is completion, anything else (socket reset,
        EOF mid-stream: the SIGKILL signature) is a broken transport the
        router must migrate."""
        finish_reason = None
        try:
            conn.request("POST", "/v1/completions",
                         json.dumps(payload), headers)
            resp = conn.getresponse()
            if resp.status != 200:
                raise RuntimeError(
                    f"replica {self.name!r} refused stream: "
                    f"{resp.status} {resp.read()[:200]!r}")
            done = False
            while not done and not stream.cancelled:
                line = resp.readline()
                if not line:
                    break  # EOF
                line = line.decode("utf-8", "replace").strip()
                if not line.startswith("data: "):
                    continue
                data = line[6:]
                if data == "[DONE]":
                    done = True
                    break
                choice = json.loads(data)["choices"][0]
                if choice.get("finish_reason") is not None:
                    finish_reason = choice["finish_reason"]
                toks = choice.get("token_ids") or []
                if toks and not stream.closed:
                    stream.on_chunk(stream, [int(t) for t in toks])
            if stream.cancelled or stream.closed:
                return
            if not done:
                raise ConnectionError(
                    f"replica {self.name!r} stream ended without [DONE]")
            self._untrack(stream)
            stream.on_done(stream, None if finish_reason in (None, "stop")
                           else finish_reason)
        except Exception as e:
            if stream.cancelled or stream.closed:
                return
            self._untrack(stream)
            stream.on_broken(stream, e)
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def _cancel(self, stream: ReplicaStream):
        self._untrack(stream)
        conn = stream._impl
        if conn is not None:
            try:
                conn.close()  # server's disconnect-cancel frees the slot
            except Exception:
                pass

    # ------------------------------------------------ cluster handoff
    def _post_json(self, path: str, body: Dict, timeout: float):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            conn.request("POST", path, json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()

    # a handoff is worth at most one prefill recompute; a transfer
    # slower than this budget is the kv-handoff-stall signature and the
    # caller falls back to recompute rather than wait
    KV_HANDOFF_TIMEOUT_S = 10.0

    def export_kv(self, tokens: Sequence[int]) -> Optional[Dict]:
        """Subprocess handoff export over the ``/v1/kv`` endpoint —
        page rows ride base64 (``encode_kv_payload`` on the worker,
        decoded here back into numpy rows)."""
        if not self.alive():
            return None
        try:
            status, obj = self._post_json(
                "/v1/kv", {"op": "export",
                           "tokens": [int(t) for t in tokens]},
                self.KV_HANDOFF_TIMEOUT_S)
            if status != 200 or not obj.get("payload"):
                return None
            return decode_kv_payload(obj["payload"])
        except Exception:
            return None

    def import_kv(self, payload: Dict) -> int:
        if not self.alive() or not payload:
            return 0
        try:
            status, obj = self._post_json(
                "/v1/kv", {"op": "import",
                           "payload": encode_kv_payload(payload)},
                self.KV_HANDOFF_TIMEOUT_S)
            return int(obj.get("adopted", 0)) if status == 200 else 0
        except Exception:
            return 0
