"""Weighted-fair multi-tenant request queue (ISSUE 12 tentpole).

The engine-core's own wait queue is FIFO — correct for one tenant,
starvation-prone for many: a batch tenant that floods 32k-token prompts
ahead of an interactive tenant owns every slot for minutes. This queue
sits IN FRONT of the engine and decides *whose* request the engine
sees next, with two mechanisms:

* **Stride scheduling** (weighted virtual time): each tenant carries a
  virtual clock; ``pop`` serves the tenant with the smallest clock and
  advances it by ``cost / weight``. Cost is the request's token
  footprint (prompt + budget), so a single huge request charges its
  tenant proportionally — a tenant with weight 4 gets 4x the token
  throughput of a weight-1 tenant under contention, and an idle
  tenant's clock is clamped to the global clock on arrival so sleeping
  never banks credit.
* **Per-tenant admission bounds**: a bounded per-tenant backlog
  (``QueueFull`` backpressure rides PR 6's taxonomy — the HTTP layer
  maps it to 429) and a concurrency share (``pop(blocked=...)`` lets
  the frontend skip tenants already holding their slot share while
  other tenants wait — work-conserving: the bound only binds under
  contention).

Tenant cardinality is bounded (``max_tenants``): past the cap, new
tenant names share the ``"other"`` bucket — the same bound the metric
labels apply — so a hostile client cycling tenant strings cannot grow
host state without limit.

Pure stdlib; importing this module must never pull in jax.
"""
from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from ..inference.errors import QueueFull

__all__ = ["DEFAULT_TENANT", "FairQueue", "parse_tenant_weights"]

DEFAULT_TENANT = "default"
OVERFLOW_TENANT = "other"


def parse_tenant_weights(spec: Optional[str]) -> Optional[Dict[str, float]]:
    """Parse the CLI grammar ``"interactive=4,batch=1"`` into a weight
    map (None/empty → None: every tenant shares the default weight)."""
    if not spec:
        return None
    out: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition("=")
        if not name or not w:
            raise ValueError(
                f"tenant weight {part!r} must be name=weight")
        weight = float(w)
        if weight <= 0:
            raise ValueError(f"tenant {name!r} weight must be > 0")
        out[name.strip()] = weight
    return out or None


class _Tenant:
    __slots__ = ("name", "weight", "vtime", "items")

    def __init__(self, name: str, weight: float, vtime: float):
        self.name = name
        self.weight = weight
        self.vtime = vtime
        self.items: deque = deque()


class FairQueue:
    """Thread-safe weighted-fair queue of opaque items keyed by tenant.

    ``submit`` enqueues (bounded per tenant, ``QueueFull`` on overflow);
    ``pop`` dequeues by smallest virtual time, optionally skipping
    ``blocked`` tenants (concurrency share enforcement); ``remove``
    supports cancellation of still-queued items.
    """

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0,
                 max_queue_per_tenant: int = 256,
                 max_tenants: int = 64):
        if max_queue_per_tenant <= 0:
            raise ValueError("max_queue_per_tenant must be positive")
        self._weights = dict(weights or {})
        self._default_weight = float(default_weight)
        self._max_queue = int(max_queue_per_tenant)
        self._max_tenants = int(max_tenants)
        self._tenants: Dict[str, _Tenant] = {}
        self._vclock = 0.0  # virtual time of the last pop
        self._lock = threading.Lock()
        self._seq = itertools.count()  # FIFO tiebreak within a tenant

    # ------------------------------------------------------------ naming
    def bucket(self, tenant: Optional[str]) -> str:
        """The bounded tenant-name bucket: configured tenants keep their
        identity, unconfigured ones do until ``max_tenants`` distinct
        names exist, then share the overflow bucket."""
        t = tenant or DEFAULT_TENANT
        if t in self._weights or t in self._tenants:
            return t
        if len(self._tenants) >= self._max_tenants:
            return OVERFLOW_TENANT
        return t

    def weight_of(self, tenant: str) -> float:
        return self._weights.get(tenant, self._default_weight)

    # ------------------------------------------------------------- queue
    def submit(self, item, tenant: Optional[str] = None,
               cost: float = 1.0):
        """Enqueue ``item`` for ``tenant``; raises the taxonomy
        ``QueueFull`` (backpressure) when the tenant's backlog is at
        capacity. Returns the bucketed tenant name the item landed on."""
        with self._lock:
            name = self.bucket(tenant)
            t = self._tenants.get(name)
            if t is None:
                # an idle/new tenant starts at the global clock: sleeping
                # must not bank credit against active tenants
                t = _Tenant(name, self.weight_of(name), self._vclock)
                self._tenants[name] = t
            if len(t.items) >= self._max_queue:
                raise QueueFull(
                    f"tenant {name!r} backlog full "
                    f"({len(t.items)}/{self._max_queue}); retry later")
            t.items.append((max(1.0, float(cost)), next(self._seq), item))
            return name

    def pop(self, blocked: Iterable[str] = ()) -> Optional[Tuple[object, str]]:
        """Dequeue the next item by weighted fairness, skipping tenants
        in ``blocked`` (at their concurrency share). Returns ``(item,
        tenant)`` or None when nothing admissible is queued."""
        blocked = set(blocked)
        with self._lock:
            best: Optional[_Tenant] = None
            for t in self._tenants.values():
                if not t.items or t.name in blocked:
                    continue
                if best is None or (t.vtime, t.name) < (best.vtime,
                                                        best.name):
                    best = t
            if best is None:
                return None
            cost, _, item = best.items.popleft()
            # idle-clamp on the way OUT too: a tenant that drained and
            # re-queued keeps pace with the global clock
            best.vtime = max(best.vtime, self._vclock) + cost / best.weight
            self._vclock = max(self._vclock, best.vtime - cost / best.weight)
            return item, best.name

    def remove(self, item) -> bool:
        """Drop a still-queued item (cancellation); False if absent."""
        with self._lock:
            for t in self._tenants.values():
                for entry in t.items:
                    if entry[2] is item:
                        t.items.remove(entry)
                        return True
        return False

    def __len__(self) -> int:
        with self._lock:
            return sum(len(t.items) for t in self._tenants.values())

    def depth(self, tenant: str) -> int:
        with self._lock:
            t = self._tenants.get(tenant)
            return len(t.items) if t else 0

    def queued_tenants(self) -> List[str]:
        """Tenants with a non-empty backlog (fairness bookkeeping for
        the frontend's concurrency-share check)."""
        with self._lock:
            return [t.name for t in self._tenants.values() if t.items]
