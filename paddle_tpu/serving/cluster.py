"""Cluster-scale serving: prefill/decode pools, cross-replica KV
handoff, and prefix-cache-aware placement (ISSUE 20 tentpole).

PR 13's router treats N replicas as N interchangeable engines: placement
is availability-only (least-loaded READY), every replica prefills AND
decodes, and each replica's prefix cache (PR 8) + host tier (PR 15) is
an island — shared-prefix tenants warm N disjoint caches and
TTFT-critical prefill compute contends with TPOT-critical decode inside
every batch. This module is the DistServe/Mooncake-shaped layer above
the router that removes both:

* **Role pools.** ``Router(pools={"prefill": k, "decode": m})`` splits
  the replica set: a fresh prompt places on a PREFILL replica with its
  token budget capped to 1 (prefill + first token — the TTFT unit of
  work), then continues on a DECODE replica via the existing
  resume-from-emitted machinery. Decode batches stay pure decode;
  prefill bursts never stretch another stream's inter-token gap.
* **KV handoff.** Between the two phases the coordinator ships the
  prompt's KV: the prefill replica exports its cached pages (the PR 15
  slab capture — ``runner.capture_pages`` + per-page blake2b digests,
  reached ONLY through the replica surface ``export_kv``), the payload
  crosses the replica transport (in-proc: shared numpy rows; subprocess:
  the ``/v1/kv`` endpoint, base64), and the decode replica
  digest-verifies and restores it into its own pool (``import_kv`` →
  ``Engine.adopt_kv_pages``) BEFORE the continuation is admitted — so
  the decode-side admission splices the shipped pages instead of
  recomputing the prefill. Every failure mode — export on a killed
  replica, a corrupt page (``kv-handoff-corrupt``), a slow transfer
  (``kv-handoff-stall``), pool pressure on the importer — degrades to
  plain resume-from-emitted recompute: the handoff is an OPTIMIZATION
  of the recovery path PR 13 already proved bit-identical, so a lost
  shipment costs latency, never a token. Budget-1 and eos-terminated
  streams simply finish on the prefill replica.
* **Cache-aware placement.** Replicas report the chain-hash digests of
  their cached prefix blocks in the readiness payload (``kv_chains``);
  the coordinator mirrors them into a per-replica view (refreshed each
  supervisor sweep, updated eagerly on handoff adoption) and scores
  placement candidates by OVERLAP DEPTH — the number of consecutive
  prompt blocks, from the root, whose chain key the replica holds —
  before load. Shared-prefix tenants converge onto warm replicas; the
  fleet's caches behave as one logical cache. A replica that omits the
  field (an older build, or a torn racy snapshot) scores 0 and routes
  availability-only — the versioned-payload fallback.
* **Autoscaling hooks.** Queue-depth and p99-TTFT signals drive pool
  resize through the existing supervised machinery: an idle replica
  REASSIGNS role toward the starved pool (counted by
  ``paddle_tpu_cluster_rebalances_total``), a sustained backlog SPAWNS
  a replica through the caller's factory, and surplus idle capacity
  DRAINS (graceful stop; the supervisor skips drained replicas instead
  of restarting them).

Threading: the coordinator's state (role map, views, phase) is guarded
by one lock; handoffs run on dedicated short-lived threads (the
``_restart`` pattern) because the in-proc prefill completion callback
fires ON the prefill replica's engine thread — calling ``export_kv``
there would marshal onto the same thread and deadlock. tpulint TPL1601
enforces that this module (and the router) reaches engines only through
the replica surface — never ``.engine``/``._fe``/``Engine(...)``.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..inference.prefix_cache import chain_keys
from ..observability import counter, gauge
from ..observability.tracing import TRACER as _TRACER
from .replica import Replica, StreamSpec

__all__ = ["ClusterCoordinator", "parse_pools"]


def parse_pools(spec: str) -> Dict[str, int]:
    """Parse a ``prefill=K,decode=M`` pool spec (the
    ``serve_llama_paged.py --pools`` flag grammar)."""
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        role, _, n = part.partition("=")
        role = role.strip()
        if role not in ("prefill", "decode") or not n.strip().isdigit():
            raise ValueError(
                f"bad pool spec {part!r}: expected prefill=K,decode=M")
        out[role] = int(n)
    if not out:
        raise ValueError("empty pool spec")
    return out


class ClusterCoordinator:
    """Pool manager + placement policy above one Router; see module
    docstring. Constructed by ``Router(pools=...)`` — not standalone."""

    def __init__(self, router, pools: Dict[str, int],
                 replica_factory: Optional[Callable] = None,
                 handoff_budget_s: float = 5.0,
                 autoscale: Optional[Dict] = None):
        self.router = router
        self.handoff_budget_s = float(handoff_budget_s)
        self.replica_factory = replica_factory
        knobs = dict(autoscale or {})
        # autoscale knobs (documented in README "Cluster serving"):
        self.min_per_role = int(knobs.get("min_per_role", 1))
        self.max_replicas = int(knobs.get("max_replicas",
                                          len(router.replicas) + 2))
        self.queue_high = int(knobs.get("queue_high", 8))
        self.ttft_slo_s = knobs.get("ttft_slo_s")
        self.idle_grace_s = float(knobs.get("idle_grace_s", 30.0))
        self._lock = threading.Lock()
        # role map keyed by replica index into router.replicas; pools
        # assign in order, leftovers default to decode
        self._roles: Dict[int, str] = {}
        want: List[str] = []
        for role in ("prefill", "decode"):
            want += [role] * int(pools.get(role, 0))
        for idx in range(len(router.replicas)):
            self._roles[idx] = (want[idx] if idx < len(want)
                                else "decode")
        self._drained: set = set()          # indices taken out of service
        self._views: Dict[int, set] = {}    # idx -> hex chain-key set
        self._page_size: Optional[int] = None
        self._eos_id: Optional[int] = None
        self._idle_since: Dict[int, float] = {}
        self._ttfts: deque = deque(maxlen=256)  # recent TTFT samples (s)
        self._m_handoffs = counter(
            "paddle_tpu_cluster_handoffs_total",
            "KV handoffs completed prefill -> decode (payload exported, "
            "digest-verified, adopted)")
        self._m_bytes = counter(
            "paddle_tpu_cluster_handoff_bytes_total",
            "KV page bytes shipped across replicas by completed handoffs")
        self._m_fallbacks = counter(
            "paddle_tpu_cluster_fallbacks_total",
            "handoffs degraded to resume-from-emitted recompute (export "
            "failure, digest mismatch, stall past budget, import "
            "pressure)")
        self._m_rebalances = counter(
            "paddle_tpu_cluster_rebalances_total",
            "pool resizes: role reassignments, spawns, and drains")
        self._m_pool = gauge(
            "paddle_tpu_cluster_pool_replicas",
            "replicas currently serving each role (drained excluded)",
            labelnames=("role",))
        self._update_pool_gauges()

    # ------------------------------------------------------------- roles
    def role_of(self, rep: Replica) -> Optional[str]:
        """``rep``'s pool role; None for drained/unknown replicas."""
        reps = self.router.replicas
        with self._lock:
            for idx, r in enumerate(reps):
                if r is rep:
                    return (None if idx in self._drained
                            else self._roles.get(idx, "decode"))
        return None

    def is_drained(self, idx: int) -> bool:
        with self._lock:
            return idx in self._drained

    def pool_sizes(self) -> Dict[str, int]:
        with self._lock:
            out = {"prefill": 0, "decode": 0}
            for idx, role in self._roles.items():
                if idx not in self._drained:
                    out[role] = out.get(role, 0) + 1
            return out

    def _update_pool_gauges(self):
        for role, n in self.pool_sizes().items():
            self._m_pool.labels(role=role).set(n)

    # --------------------------------------------------------- placement
    def outbound(self, ticket, sub: StreamSpec
                 ) -> Tuple[StreamSpec, Optional[str]]:
        """Shape one placement (called by ``Router._place``): decide the
        target ROLE pool and, for a fresh prompt that is worth
        disaggregating, cap the prefill leg's budget to one token (the
        handoff continues it). Resumed placements — cluster
        continuations and ordinary migrations alike — always target the
        decode pool: their prefill is either shipped or absorbed by the
        resume path's recompute."""
        if sub.resume_tokens:
            with ticket._cond:
                ticket.phase = "decode"
            return sub, "decode"
        ps = self._page_size
        worth = (sub.max_new_tokens > 1
                 and self.pool_sizes().get("prefill", 0) > 0
                 and (ps is None or len(sub.prompt) >= ps))
        if not worth:
            # nothing to hand off (one-token budget, or a prompt under
            # one page with no cacheable full block): run it end-to-end
            # on a decode replica
            with ticket._cond:
                ticket.phase = "decode"
            return sub, "decode"
        capped = StreamSpec(sub.prompt, 1,
                            temperature=sub.temperature, seed=sub.seed,
                            tenant=sub.tenant, deadline_s=sub.deadline_s,
                            trace=sub.trace, t_origin=sub.t_origin)
        with ticket._cond:
            ticket.phase = "prefill"
        return capped, "prefill"

    def prompt_keys(self, prompt) -> List[str]:
        """Hex chain keys for ``prompt``'s full blocks — the same
        derivation replicas report in ``kv_chains``, so key equality
        means prefix equality (replica-independently)."""
        ps = self._page_size
        if not ps:
            return []
        return [k.hex() for k in chain_keys(prompt, ps)]

    def choose(self, candidates: List[Replica],
               spec: StreamSpec) -> Replica:
        """Cache-aware pick: score each candidate by overlap depth —
        consecutive prompt blocks from the root whose chain key the
        replica's reported view holds — and take the deepest overlap,
        least-loaded on ties. With no geometry/views yet (old replicas,
        first sweep) every score is 0 and this degenerates to exactly
        the PR 13 least-loaded pick."""
        keys = self.prompt_keys(spec.prompt)
        reps = self.router.replicas
        with self._lock:
            views = {idx: self._views.get(idx, ()) for idx in
                     range(len(reps))}
        def score(rep):
            overlap = 0
            for idx, r in enumerate(reps):
                if r is rep:
                    view = views.get(idx, ())
                    for k in keys:
                        if k not in view:
                            break
                        overlap += 1
                    break
            return (-overlap, rep.inflight)
        return min(candidates, key=score)

    # ------------------------------------------------------- view upkeep
    def observe(self, rep: Replica, payload: Dict):
        """Mirror one readiness payload into the placement view (called
        from the router's supervisor sweep). A payload without
        ``kv_chains`` (older replica / torn snapshot) CLEARS nothing —
        the last good view ages in place and scoring degrades toward
        availability-only, which is the versioning contract."""
        if not isinstance(payload, dict):
            return
        reps = self.router.replicas
        idx = next((i for i, r in enumerate(reps) if r is rep), None)
        if idx is None:
            return
        with self._lock:
            if payload.get("page_size"):
                self._page_size = int(payload["page_size"])
            if payload.get("eos_id") is not None:
                self._eos_id = int(payload["eos_id"])
            chains = payload.get("kv_chains")
            if chains is not None:
                self._views[idx] = set(chains)
            # idle clock for the autoscaler's drain/reassign decisions
            if payload.get("inflight", 1) == 0:
                self._idle_since.setdefault(idx, time.perf_counter())
            else:
                self._idle_since.pop(idx, None)

    def _covers(self, rep: Replica, keys: List[str]) -> bool:
        """Does ``rep``'s reported view already hold every one of the
        prompt's chain keys? (Stale-view optimism is safe: a wrongly
        skipped shipment just recomputes on the decode side.)"""
        reps = self.router.replicas
        idx = next((i for i, r in enumerate(reps) if r is rep), None)
        if idx is None:
            return False
        with self._lock:
            view = self._views.get(idx, set())
        return all(k in view for k in keys)

    def _note_adopted(self, rep: Replica, keys: List[str]):
        """Eager view update after a verified adoption, so the decode
        placement that follows the handoff sees the warm replica NOW
        instead of a sweep later."""
        reps = self.router.replicas
        idx = next((i for i, r in enumerate(reps) if r is rep), None)
        if idx is None:
            return
        with self._lock:
            self._views.setdefault(idx, set()).update(keys)

    # ----------------------------------------------------------- handoff
    def intercept_done(self, stream, ticket) -> bool:
        """Called by ``Router._on_done`` when a stream completes
        cleanly: if it was the PREFILL leg of a pooled placement and
        the request still has budget (and did not stop at eos), detach
        it and continue on the decode pool via the handoff thread.
        Returns True when the ticket's life continues (the router must
        NOT finish it)."""
        with ticket._cond:
            phase = ticket.phase
        if phase != "prefill":
            return False
        emitted = list(ticket.tokens)
        remaining = ticket.spec.max_new_tokens - len(emitted)
        if remaining <= 0 or not emitted:
            return False  # budget was 1 after all: done where it ran
        if self._eos_id is not None and emitted[-1] == self._eos_id:
            # the first token ended the stream; a continuation would be
            # rejected (resume_tokens may not contain eos) — finish here
            return False
        resume = ticket._detach(stream)
        if resume is None:
            return False  # raced with a migration; that path owns it
        with ticket._cond:
            ticket.phase = "handoff"
        threading.Thread(
            target=self._handoff, args=(ticket, stream.replica, resume),
            name=f"cluster-handoff-{stream.replica.name}",
            daemon=True).start()
        return True

    def _handoff(self, ticket, src: Replica, resume: List[int]):
        """The handoff ladder (dedicated thread): export → (chaos) →
        import → re-place on the decode pool with ``resume``. ANY
        failure lands on the same re-place call without the import —
        the decode replica recomputes via resume-from-emitted, which
        PR 13 already proves bit-identical."""
        span = (_TRACER.start("cluster.handoff", "router",
                              parent=ticket.spec.trace, src=src.name)
                if _TRACER.enabled else None)
        t0 = time.perf_counter()
        shipped = False
        fi = self.router._fi
        try:
            if fi is not None and fi.fire("kv-handoff-stall"):
                # slow source/transfer: the sleep lands BEFORE the
                # export, so everything downstream (a replica killed
                # mid-shipment, the budget gate) sees the delay
                time.sleep(fi.param("kv-handoff-stall", "delay_ms", 50.0)
                           / 1e3)
            dst = self.router._pick(exclude=(src,), role="decode",
                                    spec=ticket.spec)
            keys = self.prompt_keys(ticket.spec.prompt)
            if dst is not None and keys and self._covers(dst, keys):
                # an earlier tenant already warmed this decode replica
                # (shared prefix): nothing to ship, nothing degraded
                shipped = True
            elif dst is not None:
                payload = src.export_kv(ticket.spec.prompt)
                if payload and fi is not None \
                        and fi.fire("kv-handoff-corrupt"):
                    self._corrupt_payload(payload, fi)
                if payload and (time.perf_counter() - t0
                                <= self.handoff_budget_s):
                    adopted = dst.import_kv(payload)
                    if adopted > 0:
                        self._note_adopted(dst, keys[:adopted])
                        self._m_handoffs.inc()
                        self._m_bytes.inc(int(payload.get("nbytes", 0)))
                        shipped = True
        except Exception:
            shipped = False  # recompute absorbs every failure mode
        if not shipped:
            self._m_fallbacks.inc()
        if span is not None:
            span.end(shipped=shipped, emitted=len(resume),
                     waited_s=round(time.perf_counter() - t0, 4))
        # decode-side continuation: scoring prefers whichever decode
        # replica now holds the prompt's chain (the one we just fed, or
        # a peer an earlier tenant warmed) — and with nothing shipped
        # this is exactly a PR 13 migration re-place
        self.router._place(ticket, resume=resume, exclude=(src,))

    @staticmethod
    def _corrupt_payload(payload: Dict, fi):
        """``kv-handoff-corrupt`` damage: flip one seed-chosen byte of
        one shipped page IN TRANSIT (on a copy — the source replica's
        slab stays clean). No doubt signal; only the decode-side digest
        verify stands between this flip and a wrong splice."""
        rows = payload.get("pages") or []
        if not rows:
            return
        j = fi.draw("kv-handoff-corrupt", len(rows))
        rows[j] = [np.array(a) for a in rows[j]]
        flat = rows[j][0].view(np.uint8).reshape(-1)
        flat[fi.draw("kv-handoff-corrupt", flat.size)] ^= 0xFF

    def note_done(self, ticket):
        """Terminal-ticket hook (from ``Router._on_done``): feed the
        TTFT sample window the autoscaler reads."""
        if ticket.t_first is not None:
            self._ttfts.append(ticket.t_first - ticket.t_submit)

    # --------------------------------------------------------- autoscale
    def _queue_depth(self, role: str) -> int:
        reps = self.router.replicas
        with self._lock:
            idxs = [i for i, r in self._roles.items()
                    if r == role and i not in self._drained]
        depth = 0
        for i in idxs:
            if i >= len(reps):
                continue
            try:
                depth += int(reps[i].ready().get("queue_depth", 0))
                depth += reps[i].inflight
            except Exception:
                continue
        return depth

    def _p99_ttft_s(self) -> Optional[float]:
        samples = list(self._ttfts)
        if len(samples) < 8:
            return None
        return float(np.percentile(np.asarray(samples), 99))

    def autoscale_tick(self, now: Optional[float] = None):
        """One autoscaler decision (called from the supervisor sweep;
        also directly by tests/benches). At most ONE action per tick —
        resize decisions observe their own effect before the next one:

        1. REASSIGN an idle surplus replica toward a starved pool
           (queue depth past ``queue_high`` while the other pool has
           more than ``min_per_role`` and an idle member).
        2. SPAWN through ``replica_factory`` when BOTH pools are
           backlogged (or p99 TTFT breaches ``ttft_slo_s``) and the
           fleet is under ``max_replicas``.
        3. DRAIN an idle surplus replica (idle past ``idle_grace_s``
           with empty queues) — graceful stop; the supervisor skips
           drained replicas instead of restarting them.
        """
        now = time.perf_counter() if now is None else now
        depth = {role: self._queue_depth(role)
                 for role in ("prefill", "decode")}
        sizes = self.pool_sizes()
        p99 = self._p99_ttft_s()
        slo_breach = (self.ttft_slo_s is not None and p99 is not None
                      and p99 > float(self.ttft_slo_s))
        # 1. role reassignment: starved pool takes an idle donor
        for starved, donor in (("prefill", "decode"),
                               ("decode", "prefill")):
            if depth[starved] < self.queue_high and not (
                    slo_breach and starved == "prefill"):
                continue
            if sizes.get(donor, 0) <= self.min_per_role:
                continue
            idle = self._idle_replica(donor, now, grace=0.0)
            if idle is None:
                continue
            with self._lock:
                self._roles[idle] = starved
                self._idle_since.pop(idle, None)
            self._m_rebalances.inc()
            self._update_pool_gauges()
            if _TRACER.enabled:
                _TRACER.instant("cluster.reassign", "router",
                                replica=self.router.replicas[idle].name,
                                to=starved, depth=depth[starved])
            return
        # 2. spawn: both pools loaded (nothing to borrow) or SLO breach
        total = sum(sizes.values())
        if self.replica_factory is not None and total < self.max_replicas \
                and (min(depth.values()) >= self.queue_high or slo_breach):
            starved = max(depth, key=lambda r: depth[r])
            self._spawn(starved)
            return
        # 3. drain surplus idle capacity
        for role in ("decode", "prefill"):
            if sizes.get(role, 0) <= self.min_per_role \
                    or depth[role] > 0:
                continue
            idle = self._idle_replica(role, now, grace=self.idle_grace_s)
            if idle is None:
                continue
            self._drain(idle)
            return

    def _idle_replica(self, role: str, now: float,
                      grace: float) -> Optional[int]:
        with self._lock:
            for idx, r in self._roles.items():
                if r != role or idx in self._drained:
                    continue
                since = self._idle_since.get(idx)
                if since is None or now - since < grace:
                    continue
                if self.router.replicas[idx].inflight == 0:
                    return idx
        return None

    def _spawn(self, role: str):
        """Grow the fleet by one replica (the caller's factory builds
        and the router's existing machinery supervises it)."""
        try:
            rep = self.replica_factory()
            if not rep.alive():
                rep.start()
        except Exception:
            return  # a failed spawn is a no-op, retried next tick
        self.router.replicas.append(rep)
        idx = len(self.router.replicas) - 1
        with self._lock:
            self._roles[idx] = role
        self._m_rebalances.inc()
        self._update_pool_gauges()
        if _TRACER.enabled:
            _TRACER.instant("cluster.spawn", "router", replica=rep.name,
                            role=role)

    def _drain(self, idx: int):
        """Take one idle replica out of service: mark drained (the
        routing and supervisor paths skip it) and stop it gracefully."""
        rep = self.router.replicas[idx]
        with self._lock:
            self._drained.add(idx)
            self._idle_since.pop(idx, None)
            self._views.pop(idx, None)
        self._m_rebalances.inc()
        self._update_pool_gauges()
        if _TRACER.enabled:
            _TRACER.instant("cluster.drain", "router", replica=rep.name)
        try:
            rep.stop()
        except Exception:
            pass
