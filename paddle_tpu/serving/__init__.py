"""paddle_tpu.serving — the async streaming front-end (ISSUE 12).

The layer that turns the paged ``inference.Engine`` into a *service*:

* :mod:`fairness` — the weighted-fair multi-tenant request queue
  (stride scheduling with per-tenant admission bounds) that sits in
  front of the engine-core scheduler, so one tenant's 32k-token batch
  flood cannot starve interactive traffic.
* :mod:`frontend` — ``ServingFrontend``: the engine-core loop on its
  own thread (every ``Engine`` call lives there — the engine is not
  thread-safe), multi-step scheduling when the queue is idle, stream
  tickets bridging harvest callbacks to any consumer (blocking
  iterators, asyncio queues), and the graceful SIGTERM drain.
* :mod:`server` — ``ApiServer``: an OpenAI-compatible streaming HTTP
  server (pure stdlib asyncio; SSE ``/v1/completions`` +
  ``/v1/chat/completions``) decoupled from the engine by the fair
  queue. tpulint rule TPL901 enforces that nothing inside this
  package's ``async def`` bodies blocks the event loop.
* :mod:`loadgen` — closed- and open-loop SLO load generation driving
  the frontend; ``bench_slo`` gates p99 TTFT/TPOT at a target QPS and
  the multi-step speedup (bench.py's ``slo_*``/``multistep_*`` keys).
* :mod:`replica` / :mod:`router` — the replica-resilience layer
  (ISSUE 13): supervised engine replicas (in-process or subprocess
  workers behind the ApiServer protocol) with split liveness/readiness,
  health-gated routing, and KV-free mid-stream request migration —
  a dead replica's streams re-admit elsewhere as prompt‖emitted and
  the client sees one uninterrupted, bit-identical token sequence.
* :mod:`cluster` — cluster-scale serving (ISSUE 20):
  ``Router(pools={"prefill": k, "decode": m})`` splits the fleet into
  role pools, ships finished prefill KV across replicas
  (digest-verified; every failure degrades to resume-from-emitted
  recompute), scores placement by prefix-chain overlap before load,
  and autoscales pools from queue-depth/p99-TTFT signals.

The package itself is stdlib+numpy; only the frontend's engine thread
ever touches jax/compiled programs — the event loop and the fair queue
never do (tpulint TPL901 keeps it that way; TPL902 additionally bans
unbounded retry loops anywhere in this package).
"""
from .cluster import ClusterCoordinator, parse_pools
from .fairness import DEFAULT_TENANT, FairQueue, parse_tenant_weights
from .frontend import ServingFrontend, StreamTicket
from .replica import InProcReplica, Replica, StreamSpec, SubprocessReplica
from .router import Router, RouterTicket

__all__ = [
    "DEFAULT_TENANT", "FairQueue", "parse_tenant_weights",
    "ServingFrontend", "StreamTicket",
    "Replica", "InProcReplica", "SubprocessReplica", "StreamSpec",
    "Router", "RouterTicket",
    "ClusterCoordinator", "parse_pools",
]
