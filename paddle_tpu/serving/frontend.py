"""The serving front-end's engine-core loop (ISSUE 12 tentpole).

``ServingFrontend`` decouples request arrival from the scheduling loop:

* **One engine thread.** The paged ``Engine`` is not thread-safe, so
  EVERY engine call (``add_request``/``step``/``cancel``) happens on the
  frontend's dedicated thread. Submitters only touch the thread-safe
  :class:`~paddle_tpu.serving.fairness.FairQueue` and their own
  :class:`StreamTicket`; the loop drains the queue into the engine,
  steps it, and completes tickets.
* **Fair admission with concurrency shares.** The loop feeds the engine
  only while it can place work NOW (free slots beyond the engine's own
  short queue), popping by weighted virtual time and skipping tenants
  already holding their weight-proportional slot share while other
  tenants wait — work-conserving: with no contention any tenant may use
  every slot. This is what bounds a batch tenant's starvation of
  interactive traffic (the ISSUE 12 fairness gate).
* **Multi-step when idle.** With arrivals queued the loop steps the
  engine one iteration at a time (fast turnover — a freed slot admits
  the next fair pick immediately); with the queue idle it hands the
  engine its full ``multi_step`` budget and the pure-decode fast path
  amortizes the host round trip (``Engine.step(n)``).
* **Graceful drain (SIGTERM).** ``drain(grace_s)`` — PR 7's preemption
  pattern applied to serving — stops admissions (``QueueFull`` to new
  submitters), lets in-flight streams finish inside the grace budget,
  then cancels the stragglers through the engine's taxonomy ``cancel``
  path so every stream terminates cleanly (finish or ``cancelled``),
  and finally stops the engine thread.

``StreamTicket`` is the submitter's handle: a thread-safe token stream
(blocking ``next_chunk``/``result`` for sync consumers, an ``on_chunk``
callback for asyncio bridging — the HTTP server passes one that trampolines
into its event loop) plus host-side TTFT/TPOT timestamps the SLO load
generator reads.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from ..inference.errors import EngineError, QueueFull
from ..observability.tracing import TRACER as _TRACER
from .fairness import DEFAULT_TENANT, FairQueue

__all__ = ["ServingFrontend", "StreamTicket"]


class StreamTicket:
    """A submitted request's stream handle. Engine-thread side pushes
    token chunks and the terminal state; any thread consumes."""

    def __init__(self, prompt, max_new_tokens: int, temperature: float,
                 seed: Optional[int], tenant: str,
                 deadline_s: Optional[float],
                 on_chunk: Optional[Callable] = None,
                 resume_tokens: Optional[List[int]] = None,
                 max_buffered: int = 4096,
                 trace: Optional[str] = None,
                 t_origin: Optional[float] = None):
        self.prompt = np.asarray(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.seed = seed
        self.tenant = tenant
        self.deadline_s = deadline_s
        # resume-from-emitted (ISSUE 13): tokens the stream already
        # delivered elsewhere; passed through to Engine.add_request —
        # only FRESH tokens ever reach this ticket's consumer
        self.resume_tokens = (list(resume_tokens)
                              if resume_tokens else None)
        self.rid: Optional[int] = None
        self.tokens: List[int] = []
        self.done = False
        self.failure_reason: Optional[str] = None
        self.cancelled = False
        self.stall_cancelled = False
        # request tracing (ISSUE 18): parent span context (wire string)
        # and the ORIGINAL submit time — a migrated stream carries its
        # first submission's clock so TTFT attribution spans replicas
        self.trace = trace
        # host-side latency marks (the SLO loadgen's measurement side)
        self.t_submit = time.perf_counter()
        self.t_origin = (float(t_origin) if t_origin is not None
                         else self.t_submit)
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self._chunks: deque = deque()
        self._cond = threading.Condition()
        self._on_chunk = on_chunk
        # slow-client accounting (ISSUE 13 satellite): chunks handed to
        # the consumer side but not yet consumed. Pull consumers ack by
        # popping (next_chunk); push bridges (the SSE writer) call
        # ``ack()`` once the bytes actually drained to the client. A
        # consumer that stops consuming while the engine keeps emitting
        # shows up as pending > 0 with a growing stall clock — the
        # frontend cancels it, freeing the slot and pages an abandoned-
        # but-connected client would otherwise pin forever.
        self.max_buffered = int(max_buffered)
        self._pending = 0
        self._t_oldest: Optional[float] = None

    # ------------------------------------------- engine-thread callbacks
    def _on_tokens(self, toks: List[int]):
        now = time.perf_counter()
        with self._cond:
            if self.t_first is None:
                self.t_first = now
            self.tokens.extend(int(t) for t in toks)
            if self._on_chunk is None:
                # pull surface only: a push bridge would double-buffer
                # every chunk here with no consumer to drain it
                self._chunks.append(list(toks))
            if self._pending == 0:
                self._t_oldest = now
            self._pending += 1
            self._cond.notify_all()
        if self._on_chunk is not None:
            self._on_chunk(list(toks))

    def _finish(self, failure_reason: Optional[str] = None):
        with self._cond:
            if self.done:
                return
            self.done = True
            self.failure_reason = failure_reason
            self.t_done = time.perf_counter()
            self._cond.notify_all()
        if self._on_chunk is not None:
            self._on_chunk(None)  # end-of-stream sentinel

    # --------------------------------------------------- consumer surface
    def ack(self, n: int = 1):
        """Consumer-side progress mark (slow-client watchdog): a push
        bridge calls this after it actually delivered a chunk (e.g. the
        SSE writer after ``drain()``); pull consumers ack implicitly by
        popping. Keeps the stall clock honest for consumers the engine
        cannot see."""
        now = time.perf_counter()
        with self._cond:
            self._pending = max(0, self._pending - int(n))
            self._t_oldest = now if self._pending else None

    def stalled_for(self, now: Optional[float] = None) -> float:
        """Seconds the oldest unconsumed chunk has been waiting (0.0
        when the consumer is keeping up). A backlog past
        ``max_buffered`` reports inf — the bounded-buffer trip wire."""
        with self._cond:
            if self._pending <= 0 or self._t_oldest is None:
                return 0.0
            if self._pending > self.max_buffered:
                return float("inf")
            return (now or time.perf_counter()) - self._t_oldest

    def next_chunk(self, timeout: Optional[float] = None):
        """Block for the next token chunk; None marks end of stream."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._chunks and not self.done:
                left = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                if left == 0.0 or not self._cond.wait(left):
                    raise TimeoutError("no chunk within timeout")
            if self._chunks:
                self._pending = max(0, self._pending - 1)
                self._t_oldest = (time.perf_counter() if self._pending
                                  else None)
                return self._chunks.popleft()
            return None

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream terminates; returns all tokens (check
        ``failure_reason`` for how it ended)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self.done:
                left = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                if left == 0.0 or not self._cond.wait(left):
                    raise TimeoutError("stream did not terminate in time")
            return list(self.tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        return (None if self.t_first is None
                else self.t_first - self.t_submit)

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean inter-token latency over the decode tail."""
        if self.t_first is None or self.t_done is None \
                or len(self.tokens) <= 1:
            return None
        return (self.t_done - self.t_first) / (len(self.tokens) - 1)


class ServingFrontend:
    """Engine-core loop thread + fair admission; see module docstring."""

    def __init__(self, engine, tenant_weights: Optional[Dict[str, float]]
                 = None, max_queue_per_tenant: int = 256,
                 max_tenants: int = 64, idle_wait_s: float = 0.02,
                 stream_stall_s: Optional[float] = None,
                 max_buffered_chunks: int = 4096,
                 ready_queue_depth: Optional[int] = None):
        self.engine = engine
        self.queue = FairQueue(weights=tenant_weights,
                               max_queue_per_tenant=max_queue_per_tenant,
                               max_tenants=max_tenants)
        self._weights = dict(tenant_weights or {})
        self._idle_wait_s = float(idle_wait_s)
        # slow-client policy (ISSUE 13 satellite): a live ticket whose
        # consumer has not made progress for stream_stall_s (or whose
        # unconsumed backlog passed max_buffered_chunks) is cancelled
        # through the engine's taxonomy path — slot and pages free
        # immediately instead of being pinned by an abandoned-but-
        # connected client. None disables the timer (pull consumers that
        # only ever call result() never ack); the buffer bound always
        # holds.
        self.stream_stall_s = (None if stream_stall_s is None
                               else float(stream_stall_s))
        self.max_buffered_chunks = int(max_buffered_chunks)
        # readiness gate (ISSUE 13): queued work beyond this depth marks
        # the replica not-ready so a router sends new streams elsewhere
        self.ready_queue_depth = int(
            ready_queue_depth if ready_queue_depth is not None
            else max(8, 4 * engine.max_slots))
        self._live: Dict[int, StreamTicket] = {}  # rid -> ticket
        self._reqs: Dict[int, object] = {}        # rid -> engine Request
        self._cancels: deque = deque()
        self._calls: deque = deque()  # (fn, box): engine-thread errands
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._draining = False
        self._force_cancel = False
        self._poisoned = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ control
    def start(self) -> "ServingFrontend":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="paddle-engine-core", daemon=True)
            self._thread.start()
        return self

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def alive(self) -> bool:
        """Liveness: the engine thread is up and not poisoned. This is
        the multi-replica supervisor's process-up check for in-process
        replicas."""
        return (self._thread is not None and self._thread.is_alive()
                and not self._poisoned)

    def readiness(self) -> Dict:
        """Readiness snapshot (ISSUE 13): the ``/readyz`` payload and
        the router's health gate. Ready = alive, not draining, the
        engine watchdog below its readiness threshold, and the combined
        queue depth under ``ready_queue_depth``. All fields are host
        ints read without the engine lock — a racy read is at worst one
        scheduling step stale, which is exactly the staleness any
        health probe has."""
        eng = self.engine
        wd = eng._watchdog.readiness()
        queued = len(self.queue) + len(eng._queue)
        ready = (self.alive and not self._draining and wd["ready"]
                 and queued <= self.ready_queue_depth)
        out = {"ready": bool(ready), "alive": self.alive,
               "draining": self._draining,
               "watchdog_level": wd["level"],
               "watchdog_mode": wd["mode"],
               # integrity quarantine (ISSUE 14): tells the router to
               # migrate IN-FLIGHT streams too, not just stop routing
               # new ones — corrupt weights poison existing streams'
               # future tokens, unlike ordinary degradation
               "quarantined": bool(wd.get("quarantined", False)),
               "queue_depth": queued,
               "active": len(eng._active),
               "inflight": len(self._live) + queued}
        # cluster placement payload (ISSUE 20): the chain-hash digests
        # of every cached prefix block (any tier — host-resident blocks
        # promote on the hit this report attracts), plus the geometry a
        # handoff peer needs. Racy-by-design like the fields above; the
        # engine thread mutates the cache dict concurrently, so a torn
        # iteration degrades to omitting the field — which is EXACTLY
        # the versioned-payload fallback the router must tolerate from
        # older replicas anyway (availability-only routing).
        try:
            pc = eng._pcache
            if pc is not None:
                out["kv_chains"] = [
                    k.hex() for k in list(pc._by_key)
                ][:self.KV_CHAINS_REPORT_MAX]
            out["page_size"] = int(eng.page_size)
            out["eos_id"] = eng.eos_id
        except Exception:  # pragma: no cover - racy dict resize
            pass
        return out

    # bound on the readiness payload's chain-digest report: 4096 hex
    # keys ≈ 128 KiB — plenty for placement scoring (it covers 4096
    # cached blocks) without turning every heartbeat into a bulk scrape
    KV_CHAINS_REPORT_MAX = 4096

    def poison(self):
        """Simulate sudden replica death (the chaos surface behind the
        ``replica-crash`` fault point for in-process replicas): the
        engine thread exits at its next loop turn WITHOUT finishing,
        cancelling, or draining anything — live tickets simply go
        silent, exactly like a SIGKILLed process's streams. The router's
        stall watchdog / liveness probe is what must notice."""
        self._poisoned = True
        self._wake.set()

    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               seed: Optional[int] = None, tenant: Optional[str] = None,
               deadline_s: Optional[float] = None,
               on_chunk: Optional[Callable] = None,
               resume_tokens: Optional[List[int]] = None,
               trace: Optional[str] = None,
               t_origin: Optional[float] = None) -> StreamTicket:
        """Enqueue a request (any thread). Raises the taxonomy
        ``QueueFull`` on backpressure or while draining.
        ``resume_tokens`` is the replica-migration resume path — see
        ``Engine.add_request``. ``trace``/``t_origin`` (ISSUE 18) are
        the upstream span context and original submit time a router or
        API caller propagates; both default to "this is the origin"."""
        if self._draining or self._stop.is_set() or self._poisoned:
            raise QueueFull("server is draining; not accepting requests")
        tenant = tenant or DEFAULT_TENANT
        ticket = StreamTicket(prompt, max_new_tokens, temperature, seed,
                              tenant, deadline_s, on_chunk=on_chunk,
                              resume_tokens=resume_tokens,
                              max_buffered=self.max_buffered_chunks,
                              trace=trace, t_origin=t_origin)
        if _TRACER.enabled:
            _TRACER.instant("frontend.submit", "frontend",
                            parent=ticket.trace, tenant=tenant,
                            prompt_len=int(ticket.prompt.size),
                            resumed=len(resume_tokens or ()))
        # token footprint as fairness cost: a 32k-token prompt charges
        # its tenant's virtual clock accordingly
        cost = float(ticket.prompt.size + ticket.max_new_tokens)
        ticket.tenant = self.queue.submit(ticket, tenant=tenant, cost=cost)
        self._wake.set()
        return ticket

    def call(self, fn: Callable, timeout: float = 10.0):
        """Run ``fn()`` ON the engine thread and block for its result
        (any OTHER thread). The engine is single-threaded by contract —
        every ``Engine`` touch must happen on the loop below — so
        cross-thread errands (the cluster KV handoff's export/adopt,
        ISSUE 20) marshal through this deque exactly like ``_cancels``
        do. Raises whatever ``fn`` raised, or ``TimeoutError`` when the
        loop did not get to it in time (a dead/poisoned engine thread
        degrades the caller to its fallback, never a hang)."""
        if not self.alive:
            raise RuntimeError("engine thread is not running")
        box = {"evt": threading.Event(), "result": None, "exc": None}
        self._calls.append((fn, box))
        self._wake.set()
        if not box["evt"].wait(timeout):
            raise TimeoutError("engine thread did not run the call "
                               f"within {timeout}s")
        if box["exc"] is not None:
            raise box["exc"]
        return box["result"]

    # ---------------------------------------------- cluster KV handoff
    def export_kv(self, tokens, timeout: float = 10.0) -> Optional[Dict]:
        """Capture the prompt's cached KV pages into a handoff payload
        (ISSUE 20, prefill side). Runs on the engine thread via
        :meth:`call`; None when nothing is cached."""
        return self.call(
            lambda: self.engine._cache.export_handoff(tokens), timeout)

    def import_kv(self, payload, timeout: float = 10.0) -> int:
        """Adopt a shipped handoff payload into this replica's pool
        (ISSUE 20, decode side). Digest-verified by the engine; returns
        pages adopted (0 = caller falls back to recompute)."""
        return self.call(
            lambda: self.engine.adopt_kv_pages(payload), timeout)

    def cancel(self, ticket: StreamTicket):
        """Cancel a stream (any thread): a queued ticket dies in the
        fair queue; an admitted one goes through ``Engine.cancel`` on
        the engine thread — slot and pages recycle immediately."""
        ticket.cancelled = True
        self._cancels.append(ticket)
        self._wake.set()

    def drain(self, grace_s: float = 30.0) -> bool:
        """Graceful shutdown: refuse new work, finish in-flight streams
        within ``grace_s``, cancel stragglers cleanly, stop the engine
        thread. Blocking (call off the event loop); True if every
        stream finished without a forced cancel."""
        self._draining = True
        self._wake.set()
        finished = self._drained.wait(timeout=max(0.0, grace_s))
        if not finished:
            self._force_cancel = True
            self._wake.set()
            self._drained.wait(timeout=10.0)
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        return finished

    def shutdown(self):
        """Immediate stop (tests): cancel everything, join the thread."""
        # tpulint: disable=TPL1503 -- idempotent latch: racing callers all
        # write the same True values and the engine thread only reads them
        if not self._draining:
            self._draining = True
            self._force_cancel = True
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    # ----------------------------------------------------- engine thread
    def _slot_share(self, tenant: str, contenders: List[str]) -> int:
        """Weight-proportional slot share for ``tenant`` among the
        tenants currently contending (queued or holding slots)."""
        total = sum(self.queue.weight_of(t) for t in contenders) or 1.0
        w = self.queue.weight_of(tenant)
        return max(1, int(round(self.engine.max_slots * w / total)))

    def _contenders(self) -> List[str]:
        live_tenants = {t.tenant for t in self._live.values()}
        return sorted(live_tenants | set(self.queue.queued_tenants()))

    def _feed(self):
        """Admit from the fair queue while the engine can place work NOW
        — free slots beyond its own (short) wait queue.

        Concurrency shares: with an explicit tenant-weight map the
        shares are HARD — every configured tenant counts as a contender
        whether or not it has work queued right now, so a batch tenant
        caps at its weight-proportional slot count and the interactive
        tenant's slots stay warm between its arrivals (the weights ARE
        the reservation; a tenant that wants work-conserving behavior
        gets it by not being weighted). Without a weight map the share
        check only binds under live contention (fully work-conserving
        single-tenant/equal-weight behavior)."""
        eng = self.engine
        while len(eng._free_slots) > len(eng._queue):
            if self._weights:
                contenders = sorted(set(self._weights)
                                    | {t.tenant
                                       for t in self._live.values()}
                                    | set(self.queue.queued_tenants()))
            else:
                contenders = self._contenders()
            blocked = []
            if len(contenders) > 1:
                held: Dict[str, int] = {}
                for t in self._live.values():
                    held[t.tenant] = held.get(t.tenant, 0) + 1
                blocked = [t for t in contenders
                           if held.get(t, 0)
                           >= self._slot_share(t, contenders)]
            popped = self.queue.pop(blocked=blocked)
            if popped is None and blocked and not self._weights:
                popped = self.queue.pop()  # work-conserving fallback
            if popped is None:
                break
            ticket, tenant = popped
            ticket.tenant = tenant
            if ticket.cancelled:
                ticket._finish("cancelled")
                continue
            if _TRACER.enabled:
                # retroactive FairQueue-wait span: submit -> this pop
                now = time.perf_counter()
                _TRACER.complete(
                    "frontend.queue", "frontend",
                    time.time() - (now - ticket.t_submit),
                    now - ticket.t_submit, parent=ticket.trace,
                    tenant=tenant)
            try:
                req = eng.add_request(
                    ticket.prompt, ticket.max_new_tokens,
                    on_token=ticket._on_tokens,
                    temperature=ticket.temperature, seed=ticket.seed,
                    deadline_s=ticket.deadline_s, tenant=tenant,
                    resume_tokens=ticket.resume_tokens,
                    trace=ticket.trace, t_submit=ticket.t_origin)
            except EngineError as e:
                ticket._finish(getattr(e, "reason", "engine"))
                continue
            except ValueError:
                ticket._finish("validation")
                continue
            ticket.rid = req.rid
            self._live[req.rid] = ticket
            self._reqs[req.rid] = req

    def _apply_calls(self):
        """Drain cross-thread errands (engine thread): each ``call()``
        runs here, between scheduling steps, so the engine stays
        single-threaded while other threads (the cluster handoff) get
        results back."""
        while self._calls:
            fn, box = self._calls.popleft()
            try:
                box["result"] = fn()
            except Exception as e:  # noqa: BLE001 - travels to caller
                box["exc"] = e
            box["evt"].set()

    def _apply_cancels(self):
        while self._cancels:
            ticket = self._cancels.popleft()
            if ticket.done:
                continue
            if ticket.rid is not None:
                self.engine.cancel(ticket.rid)
            elif self.queue.remove(ticket):
                ticket._finish("cancelled")
            # else: between pop and add_request — the cancelled flag in
            # _feed catches it

    def _cancel_stalled(self):
        """Slow-client watchdog (ISSUE 13 satellite): cancel live
        tickets whose consumer stopped making progress — stalled past
        ``stream_stall_s``, or backlogged past ``max_buffered_chunks``
        (``stalled_for`` reports inf for those regardless of the
        timer). Cancellation rides the engine's taxonomy path, so the
        slot and pages recycle immediately."""
        if not self._live:
            return
        now = time.perf_counter()
        for rid, ticket in list(self._live.items()):
            stalled = ticket.stalled_for(now)
            over = (self.stream_stall_s is not None
                    and stalled > self.stream_stall_s)
            if not over and stalled != float("inf"):
                continue
            ticket.stall_cancelled = True
            self.engine.cancel(rid)
            try:
                from ..observability import counter

                counter("paddle_tpu_slow_client_cancels_total",
                        "streams cancelled because the consumer "
                        "stalled past the stream-stall budget or the "
                        "per-stream chunk buffer bound").inc()
            except Exception:  # pragma: no cover - stdlib-only contexts
                pass

    def _complete(self):
        """Finish tickets whose engine request reached a terminal
        state (the engine has no completion callback — harvest only
        streams tokens)."""
        if not self._live:
            return
        done_rids = []
        for rid, ticket in self._live.items():
            req = self._reqs.get(rid)
            if req is None or req.done:
                done_rids.append(rid)
                ticket._finish(req.failure_reason if req is not None
                               else "engine")
        for rid in done_rids:
            self._live.pop(rid, None)
            self._reqs.pop(rid, None)

    def _loop(self):
        eng = self.engine
        try:
            while not self._stop.is_set():
                if self._poisoned:
                    # sudden-death chaos surface: vanish mid-flight.
                    # Live tickets stay unfinished on purpose — the
                    # router's failover machinery is what must react.
                    return
                self._apply_cancels()
                self._apply_calls()
                self._cancel_stalled()
                if self._force_cancel:
                    for rid in list(self._live):
                        eng.cancel(rid)
                    while True:
                        popped = self.queue.pop()
                        if popped is None:
                            break
                        popped[0]._finish("cancelled")
                # draining still FEEDS: a ticket accepted into the fair
                # queue is in-flight work the drain must finish (submit
                # is what the drain gate refuses)
                self._feed()
                if eng._queue or eng._active:
                    # arrivals waiting → single iterations for fast slot
                    # turnover; idle queue → the multi-step fast path
                    n = 1 if len(self.queue) else None
                    eng.step(n)
                    self._complete()
                    if eng._watchdog.quarantined:
                        # integrity fail-stop (ISSUE 14): the engine
                        # refuses to mint tokens through corrupt
                        # weights, so step() is a no-op — idle-wait
                        # instead of hot-spinning until the router
                        # fences this replica and migrates its streams.
                        # The KV host tier drains with the replica
                        # (ISSUE 15): spill state captured on corrupt
                        # hardware is never carried into the restart.
                        eng._cache.shutdown_tier()
                        self._wake.wait(timeout=self._idle_wait_s)
                        self._wake.clear()
                    continue
                self._complete()
                if self._draining and not self._live \
                        and not len(self.queue):
                    self._drained.set()
                    if self._stop.is_set():
                        break
                # idle: sleep until a submit/cancel/drain wakes us
                self._wake.wait(timeout=self._idle_wait_s)
                self._wake.clear()
        finally:
            # every way out of the engine thread — drain, shutdown,
            # poison (the replica-crash chaos surface), an escape —
            # stops the KV-tier spill worker too (ISSUE 15): a replica
            # restart builds a fresh engine, and the dead incarnation
            # must not keep a live thread queued on its old pool
            try:
                eng._cache.shutdown_tier()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
            # fail pending cross-thread errands NOW instead of letting
            # their callers ride out the full call() timeout
            while self._calls:
                _fn, box = self._calls.popleft()
                box["exc"] = RuntimeError("engine thread exited")
                box["evt"].set()
            self._drained.set()
