"""OpenAI-compatible streaming HTTP server (ISSUE 12 tentpole).

Pure stdlib asyncio — no web framework in the image, none needed: the
protocol surface is small (two POST endpoints + health), and owning the
socket keeps the event loop honest (tpulint TPL901 flags any blocking
call inside this package's ``async def`` bodies — the engine lives on
the frontend's thread, the loop only ever awaits).

Endpoints (the vLLM-compatible subset):

* ``POST /v1/completions`` — ``prompt`` is a token-id list (the OpenAI
  API's native alternative form) or a string (byte-level encoded into
  the model's vocab — these are research checkpoints without a
  tokenizer); ``stream: true`` serves SSE chunks carrying both rendered
  ``text`` and the exact ``token_ids`` (the identity tests' surface),
  terminated by ``data: [DONE]``.
* ``POST /v1/chat/completions`` — messages flattened and encoded the
  same way; chunks carry ``delta.content`` (+ ``token_ids``).
* ``GET /healthz`` — LIVENESS: 200 whenever the process answers (even
  draining/degraded — only a dead replica fails liveness; the payload
  carries ``draining`` for the curious).
* ``GET /readyz`` — READINESS (ISSUE 13): 200 only when fit for NEW
  traffic — not draining, engine watchdog below its degradation
  threshold, queue depth in bounds; 503 + ``Retry-After`` otherwise.
  The multi-replica router (``serving/router.py``) gates routing here.
* ``GET /v1/models`` — the single configured model id.

Failover: completions accept ``resume_tokens`` (tokens the stream
already emitted on a dead replica) — the engine re-admits
prompt‖emitted and streams only the continuation, so a router can
splice one uninterrupted client stream across replica deaths. 429s
carry ``Retry-After`` derived from queue depth; engine-scoped faults
map to 503 + taxonomy slug, never a bare 500.

Tenancy: ``X-Tenant`` header (or the OpenAI ``user`` field) keys
admission control and weighted fairness; unset lands on the default
tenant. Backpressure (``QueueFull``) maps to 429, validation to 400 —
the taxonomy slugs ride the error body.

Shutdown: SIGTERM/SIGINT sets draining (new requests 503/429), lets
in-flight streams finish inside the grace budget via
``ServingFrontend.drain`` (run in an executor — it blocks), cancels
stragglers cleanly (their streams end with ``finish_reason:
"cancelled"``), then closes the listener.
"""
from __future__ import annotations

import asyncio
import json
import signal
from typing import Dict, List, Optional, Tuple

from ..inference.errors import EngineError, QueueFull, RequestError
from .frontend import ServingFrontend

__all__ = ["ApiServer", "encode_text", "render_tokens"]

_MAX_BODY = 8 << 20  # request bodies beyond 8 MiB are refused


def encode_text(text: str, vocab_size: int) -> List[int]:
    """Deterministic byte-level text→token-id encoding for checkpoints
    without a tokenizer: each UTF-8 byte maps into the vocab."""
    return [b % vocab_size for b in text.encode("utf-8")]


def render_tokens(toks: List[int]) -> str:
    """Token ids rendered as text (`` 17 4 99``): reversible, and what
    the smoke/identity tests parse back."""
    return "".join(f" {t}" for t in toks)


class ApiServer:
    """See module docstring. ``serve_forever`` blocks until SIGTERM."""

    def __init__(self, frontend: ServingFrontend, host: str = "127.0.0.1",
                 port: int = 0, model_name: str = "paddle-tpu",
                 grace_s: float = 30.0):
        self.frontend = frontend
        self.host = host
        self.port = port
        self.model_name = model_name
        self.grace_s = float(grace_s)
        self._server: Optional[asyncio.AbstractServer] = None
        self._stop: Optional[asyncio.Event] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.vocab_size = int(frontend.engine.cfg.vocab_size)
        max_pos = int(frontend.engine.cfg.max_position)
        self.default_max_tokens = min(64, max_pos // 4)

    # ------------------------------------------------------------ lifecycle
    async def start(self):
        self._stop = asyncio.Event()
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self.frontend.start()
        return self

    async def serve_until_signal(self):
        """Install SIGTERM/SIGINT handlers, serve, drain on signal."""
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, self._stop.set)
            except NotImplementedError:  # non-unix event loops
                pass
        await self._stop.wait()
        await self.shutdown()

    async def shutdown(self):
        """Drain in-flight streams (grace-bounded), then close. The
        blocking ``frontend.drain`` runs in the default executor so the
        loop keeps pumping the very streams it is draining."""
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.frontend.drain, self.grace_s)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    def request_stop(self):
        """Thread-safe stop trigger (tests / self-smoke): trampolines
        onto the event loop — asyncio.Event is not thread-safe."""
        if self._stop is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(self._stop.set)

    # ------------------------------------------------------------ plumbing
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                method, path, headers, body = req
                keep = await self._route(method, path, headers, body,
                                         writer)
                if not keep:
                    break
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass  # client went away; per-request cancel already handled
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    async def _read_request(self, reader) -> Optional[Tuple]:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError):
            return None
        lines = head.decode("latin-1").split("\r\n")
        parts = lines[0].split()
        if len(parts) < 2:
            return None
        method, path = parts[0].upper(), parts[1]
        headers: Dict[str, str] = {}
        for ln in lines[1:]:
            name, _, value = ln.partition(":")
            if _:
                headers[name.strip().lower()] = value.strip()
        length = int(headers.get("content-length", "0") or "0")
        if length > _MAX_BODY:
            return None
        body = await reader.readexactly(length) if length else b""
        return method, path, headers, body

    @staticmethod
    async def _send(writer, status: int, payload: dict,
                    keep_alive: bool = True,
                    headers: Optional[Dict[str, str]] = None) -> bool:
        body = json.dumps(payload).encode()
        phrase = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  429: "Too Many Requests", 500: "Internal Server Error",
                  503: "Service Unavailable"}.get(status, "OK")
        conn = "keep-alive" if keep_alive else "close"
        extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
        writer.write(
            f"HTTP/1.1 {status} {phrase}\r\n"
            f"Content-Type: application/json\r\n{extra}"
            f"Content-Length: {len(body)}\r\nConnection: {conn}\r\n"
            f"\r\n".encode() + body)
        await writer.drain()
        return keep_alive

    def _retry_after_s(self) -> int:
        """``Retry-After`` seconds for 429/503 responses, derived from
        the queue depth the refused request would have waited behind:
        roughly one second per max_slots-wide wave still queued, clamped
        to [1, 30] so a hiccup never advertises an hour."""
        eng = self.frontend.engine
        depth = len(self.frontend.queue) + len(eng._queue)
        return max(1, min(30, -(-depth // max(1, eng.max_slots))))

    async def _route(self, method, path, headers, body, writer) -> bool:
        if method == "GET" and path in ("/healthz", "/health"):
            # LIVENESS (ISSUE 13): the split health surface. Answering
            # at all means the process and event loop are up — always
            # 200, even draining or degraded, so a supervisor only
            # restarts a replica that is actually dead. Routability
            # lives on /readyz.
            return await self._send(writer, 200, {
                "status": "ok",
                "draining": bool(self.frontend.draining)})
        if method == "GET" and path == "/readyz":
            # READINESS: fit for NEW traffic — not draining, watchdog
            # below its degradation threshold, queue depth in bounds.
            # The multi-replica router health-gates routing on this.
            ready = self.frontend.readiness()
            if ready["ready"]:
                return await self._send(writer, 200, {
                    "status": "ready", **ready})
            return await self._send(
                writer, 503, {"status": "not-ready", **ready},
                headers={"Retry-After": str(self._retry_after_s())})
        if method == "GET" and path == "/v1/models":
            return await self._send(writer, 200, {
                "object": "list",
                "data": [{"id": self.model_name, "object": "model"}]})
        if method == "GET" and path == "/debug/trace":
            # live trace scrape (ISSUE 18): the tracer ring, oldest
            # first — tools/trace_tpu.py converts it to Chrome
            # trace-event JSON. Served only in mode "on" (flight-only
            # records for postmortems but doesn't expose a live feed).
            from ..observability.tracing import TRACER
            if not TRACER.live:
                return await self._send(writer, 404, _err(
                    "tracing_off",
                    f"tracing mode is {TRACER.mode!r}; start with "
                    "--trace on to serve live snapshots"))
            return await self._send(writer, 200, {
                "mode": TRACER.mode, "process": TRACER.process,
                "capacity": TRACER.capacity,
                "records": TRACER.snapshot()})
        if method == "POST" and path == "/v1/kv":
            # cluster KV handoff endpoint (ISSUE 20): the subprocess
            # replica transport's ship/adopt surface. export captures
            # the prompt's cached pages (engine thread, blocking — runs
            # in the executor so the event loop keeps pumping streams);
            # import digest-verifies and restores a shipped payload.
            try:
                payload = json.loads(body.decode() or "{}")
            except (ValueError, UnicodeDecodeError):
                return await self._send(writer, 400, _err(
                    "invalid_json", "body is not valid JSON"))
            loop = asyncio.get_running_loop()
            try:
                op = payload.get("op")
                if op == "export":
                    toks = payload.get("tokens") or []
                    out = await loop.run_in_executor(
                        None, self.frontend.export_kv, toks)
                    from .replica import encode_kv_payload

                    return await self._send(writer, 200, {
                        "payload": (encode_kv_payload(out)
                                    if out else None)})
                if op == "import":
                    from .replica import decode_kv_payload

                    shipped = payload.get("payload") or {}
                    adopted = await loop.run_in_executor(
                        None, self.frontend.import_kv,
                        decode_kv_payload(shipped) if shipped else {})
                    return await self._send(writer, 200,
                                            {"adopted": int(adopted)})
                return await self._send(writer, 400, _err(
                    "validation", "op must be 'export' or 'import'"))
            except Exception as e:  # a failed handoff is a recompute
                # on the caller's side, never a wedged endpoint
                return await self._send(writer, 503, _err(
                    "kv_handoff", f"{type(e).__name__}: {e}"))
        if method == "POST" and path in ("/v1/completions",
                                         "/v1/chat/completions"):
            try:
                payload = json.loads(body.decode() or "{}")
            except (ValueError, UnicodeDecodeError):
                return await self._send(writer, 400, _err(
                    "invalid_json", "body is not valid JSON"))
            try:
                return await self._completions(
                    payload, headers, writer,
                    chat=path.endswith("chat/completions"))
            except (ConnectionResetError, BrokenPipeError, OSError):
                raise  # client went away — the conn handler's cleanup
            except Exception as e:  # defense in depth: taxonomy 500,
                # never a silently dropped connection (ISSUE 13
                # satellite — engine-scoped faults map to the taxonomy)
                try:
                    return await self._send(writer, 500, _err(
                        "internal",
                        f"{type(e).__name__}: {e}"), keep_alive=False)
                except (ConnectionResetError, BrokenPipeError, OSError):
                    return False
        return await self._send(writer, 404, _err(
            "not_found", f"no route {method} {path}"))

    # --------------------------------------------------------- completions
    def _prompt_ids(self, payload: dict, chat: bool) -> List[int]:
        if chat:
            msgs = payload.get("messages")
            if not isinstance(msgs, list) or not msgs:
                raise ValueError("chat needs a non-empty messages list")
            ids: List[int] = []
            for m in msgs:
                content = m.get("content", "")
                if isinstance(content, list):  # OpenAI content parts
                    content = "".join(p.get("text", "") for p in content
                                      if isinstance(p, dict))
                ids.extend(encode_text(
                    f"{m.get('role', 'user')}: {content}\n",
                    self.vocab_size))
            return ids
        prompt = payload.get("prompt")
        if isinstance(prompt, str):
            return encode_text(prompt, self.vocab_size)
        if isinstance(prompt, list) and prompt \
                and all(isinstance(t, int) for t in prompt):
            return list(prompt)
        raise ValueError(
            "prompt must be a string or a list of token ids")

    async def _completions(self, payload, headers, writer,
                           chat: bool) -> bool:
        try:
            ids = self._prompt_ids(payload, chat)
        except ValueError as e:
            return await self._send(writer, 400,
                                    _err("validation", str(e)))
        tenant = headers.get("x-tenant") or payload.get("user") or None
        # trace propagation (ISSUE 18): a router/client that carries a
        # span context sends it as a header; the engine's spans for this
        # request then join the CALLER's trace (how a subprocess
        # replica's half of a migrated stream stays contiguous)
        trace = headers.get("x-trace-context") or None
        max_tokens = int(payload.get("max_tokens",
                                     self.default_max_tokens))
        temperature = float(payload.get("temperature", 0.0))
        seed = payload.get("seed")
        stream = bool(payload.get("stream", False))
        deadline_ms = payload.get("deadline_ms")
        resume = payload.get("resume_tokens")
        if resume is not None and not (
                isinstance(resume, list)
                and all(isinstance(t, int) for t in resume)):
            return await self._send(writer, 400, _err(
                "validation", "resume_tokens must be a list of ints"))
        loop = asyncio.get_running_loop()
        chunks: asyncio.Queue = asyncio.Queue()

        def on_chunk(chunk):  # engine thread → event loop
            loop.call_soon_threadsafe(chunks.put_nowait, chunk)

        try:
            ticket = self.frontend.submit(
                ids, max_tokens, temperature=temperature,
                seed=int(seed) if seed is not None else None,
                tenant=tenant,
                deadline_s=(float(deadline_ms) / 1e3
                            if deadline_ms is not None else None),
                on_chunk=on_chunk, resume_tokens=resume, trace=trace)
        except QueueFull as e:
            # backpressure carries a when-to-come-back hint (ISSUE 13
            # satellite): derived from the depth of the queue the
            # request would have waited behind
            return await self._send(
                writer, 429, _err("queue_full", str(e)),
                headers={"Retry-After": str(self._retry_after_s())})
        except (EngineError, ValueError) as e:
            reason = getattr(e, "reason", "validation")
            if isinstance(e, EngineError) and not isinstance(
                    e, (RequestError, ValueError)):
                # engine-scoped fault at submission: the server, not
                # the request, is at fault — 503 with the taxonomy
                # slug, never a bare 500
                return await self._send(
                    writer, 503, _err(reason, str(e)),
                    headers={"Retry-After": str(self._retry_after_s())})
            return await self._send(writer, 400, _err(reason, str(e)))
        rid = f"{'chatcmpl' if chat else 'cmpl'}-{id(ticket) & 0xFFFFFF:x}"
        if stream:
            return await self._stream(ticket, rid, chat, chunks, writer)
        return await self._unary(ticket, rid, chat, chunks, writer)

    # failure reasons where the SERVER (not the request) is at fault:
    # a unary response maps these to 503 + the taxonomy slug instead of
    # a 200 with a surprising finish_reason (ISSUE 13 satellite)
    _ENGINE_SCOPED_REASONS = frozenset(
        {"engine", "step_fault", "unhandled", "pool_exhausted",
         "retries_exhausted"})

    async def _unary(self, ticket, rid, chat, chunks, writer) -> bool:
        while await chunks.get() is not None:
            ticket.ack()  # the server IS the consumer here: chunks are
            # accumulated on receipt, so receipt is consumption
        reason = _finish_reason(ticket)
        if reason in self._ENGINE_SCOPED_REASONS:
            return await self._send(
                writer, 503, _err(reason,
                                  "request failed on an engine-scoped "
                                  "fault; safe to retry"),
                headers={"Retry-After": str(self._retry_after_s())})
        text = render_tokens(ticket.tokens)
        if chat:
            choice = {"index": 0, "finish_reason": reason,
                      "message": {"role": "assistant", "content": text},
                      "token_ids": list(ticket.tokens)}
            obj = "chat.completion"
        else:
            choice = {"index": 0, "finish_reason": reason, "text": text,
                      "token_ids": list(ticket.tokens)}
            obj = "text_completion"
        return await self._send(writer, 200, {
            "id": rid, "object": obj, "model": self.model_name,
            "choices": [choice],
            "usage": {"prompt_tokens": int(ticket.prompt.size),
                      "completion_tokens": len(ticket.tokens)}})

    async def _stream(self, ticket, rid, chat, chunks, writer) -> bool:
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-cache\r\n"
                     b"Connection: close\r\n\r\n")
        obj = "chat.completion.chunk" if chat else "text_completion"
        try:
            await writer.drain()
            while True:
                chunk = await chunks.get()
                if chunk is None:
                    break
                if chat:
                    choice = {"index": 0, "finish_reason": None,
                              "delta": {"content": render_tokens(chunk)},
                              "token_ids": list(chunk)}
                else:
                    choice = {"index": 0, "finish_reason": None,
                              "text": render_tokens(chunk),
                              "token_ids": list(chunk)}
                writer.write(_sse({"id": rid, "object": obj,
                                   "model": self.model_name,
                                   "choices": [choice]}))
                await writer.drain()
                # the chunk reached the client's socket buffer — ack so
                # the frontend's slow-client watchdog sees progress; a
                # stalled client blocks this drain, the ack clock
                # stops, and the stream is cancelled (slot/pages freed)
                ticket.ack()
            final = {"index": 0, "finish_reason": _finish_reason(ticket),
                     "token_ids": []}
            if chat:
                final["delta"] = {}
            else:
                final["text"] = ""
            writer.write(_sse({"id": rid, "object": obj,
                               "model": self.model_name,
                               "choices": [final]}))
            writer.write(b"data: [DONE]\n\n")
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError):
            # client hung up mid-stream: cancel so the engine frees the
            # slot and pages immediately (the taxonomy 'cancelled' path)
            self.frontend.cancel(ticket)
        return False  # SSE responses close the connection


def _sse(payload: dict) -> bytes:
    return b"data: " + json.dumps(payload).encode() + b"\n\n"


def _err(code: str, message: str) -> dict:
    return {"error": {"type": code, "message": message}}


def _finish_reason(ticket) -> str:
    if ticket.failure_reason:
        return ticket.failure_reason
    return "stop"
