"""paddle.distribution parity (reference: python/paddle/distribution/ —
Distribution base + Normal/Uniform/Categorical/Bernoulli/… and
kl_divergence). jax.random-backed sampling; log_prob/entropy as taped ops.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework import random as _random
from ..framework.tensor import Tensor, apply_op

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Exponential", "Laplace", "LogNormal", "kl_divergence",
           "register_kl",
           # families tail (r5)
           "Beta", "Gamma", "Dirichlet", "Multinomial", "Binomial",
           "Poisson", "Geometric", "Gumbel", "Cauchy", "StudentT",
           "MultivariateNormal", "ContinuousBernoulli", "Independent",
           "TransformedDistribution", "ExponentialFamily", "ChiSquared",
           # transforms (r5)
           "Transform", "AbsTransform", "AffineTransform",
           "ChainTransform", "ExpTransform", "PowerTransform",
           "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
           "StackTransform", "StickBreakingTransform", "TanhTransform",
           "IndependentTransform"]


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, jnp.float32) if not isinstance(
        x, jnp.ndarray) else x


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        lp = self.log_prob(value)
        return apply_op(jnp.exp, lp)

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other) -> Tensor:
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(_random.op_key(), shape, jnp.float32)
        return Tensor._wrap(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value):
        loc, scale = self.loc, self.scale
        return apply_op(
            lambda v: -((v - loc) ** 2) / (2 * scale ** 2)
            - jnp.log(scale) - 0.5 * math.log(2 * math.pi),
            value,
        )

    def entropy(self):
        return Tensor._wrap(
            0.5 + 0.5 * math.log(2 * math.pi)
            + jnp.log(self.scale) * jnp.ones(self.batch_shape))

    @property
    def mean(self):
        return Tensor._wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor._wrap(
            jnp.broadcast_to(self.scale ** 2, self.batch_shape))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_random.op_key(), shape, jnp.float32)
        return Tensor._wrap(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        low, high = self.low, self.high
        return apply_op(
            lambda v: jnp.where((v >= low) & (v < high),
                                -jnp.log(high - low), -jnp.inf), value)

    def entropy(self):
        return Tensor._wrap(jnp.log(self.high - self.low)
                            * jnp.ones(self.batch_shape))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is not None:
            self.logits = _arr(logits)
        else:
            self.logits = jnp.log(jnp.clip(_arr(probs), 1e-30))
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return Tensor._wrap(jax.nn.softmax(self.logits, axis=-1))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(jax.random.categorical(
            _random.op_key(), self.logits, shape=shape))

    def log_prob(self, value):
        logits = self.logits
        return apply_op(
            lambda v: jnp.take_along_axis(
                jax.nn.log_softmax(logits, axis=-1),
                v[..., None].astype(jnp.int32), axis=-1)[..., 0],
            value,
        )

    def entropy(self):
        p = jax.nn.softmax(self.logits, axis=-1)
        lp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor._wrap(-jnp.sum(p * lp, axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_arr = _arr(probs)
        super().__init__(self.probs_arr.shape)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(jax.random.bernoulli(
            _random.op_key(), self.probs_arr, shape).astype(jnp.float32))

    def log_prob(self, value):
        p = jnp.clip(self.probs_arr, 1e-7, 1 - 1e-7)
        return apply_op(
            lambda v: v * jnp.log(p) + (1 - v) * jnp.log1p(-p), value)

    def entropy(self):
        p = jnp.clip(self.probs_arr, 1e-7, 1 - 1e-7)
        return Tensor._wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(jax.random.exponential(
            _random.op_key(), shape, jnp.float32) / self.rate)

    def log_prob(self, value):
        rate = self.rate
        return apply_op(lambda v: jnp.log(rate) - rate * v, value)

    def entropy(self):
        return Tensor._wrap(1.0 - jnp.log(self.rate))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(self.loc + self.scale * jax.random.laplace(
            _random.op_key(), shape, jnp.float32))

    def log_prob(self, value):
        loc, scale = self.loc, self.scale
        return apply_op(
            lambda v: -jnp.abs(v - loc) / scale
            - jnp.log(2 * scale), value)

    def entropy(self):
        return Tensor._wrap(1.0 + jnp.log(2 * self.scale))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        self._normal = Normal(loc, scale)
        super().__init__(self._normal.batch_shape)

    def sample(self, shape=(), seed=0):
        return Tensor._wrap(jnp.exp(self._normal.sample(shape)._data))

    def log_prob(self, value):
        loc, scale = self.loc, self.scale
        return apply_op(
            lambda v: -((jnp.log(v) - loc) ** 2) / (2 * scale ** 2)
            - jnp.log(v * scale) - 0.5 * math.log(2 * math.pi), value)


# ------------------------------------------------------------ KL registry --

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Reference: paddle.distribution.register_kl decorator."""
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor._wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return Tensor._wrap(
        jnp.log((q.high - q.low) / (p.high - p.low))
        + jnp.where((q.low <= p.low) & (p.high <= q.high), 0.0, jnp.inf))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    pp = jax.nn.softmax(p.logits, axis=-1)
    return Tensor._wrap(jnp.sum(
        pp * (jax.nn.log_softmax(p.logits, -1)
              - jax.nn.log_softmax(q.logits, -1)), axis=-1))


# families + transforms tail live in submodules; import AFTER the base
# machinery so their register_kl decorators land in this registry
from .transform import (  # noqa: E402
    Transform, AbsTransform, AffineTransform, ChainTransform,
    ExpTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform,
    TanhTransform, IndependentTransform)
from .families import (  # noqa: E402
    Beta, Gamma, Dirichlet, Multinomial, Binomial, Poisson, Geometric,
    Gumbel, Cauchy, StudentT, MultivariateNormal, ContinuousBernoulli,
    Independent, TransformedDistribution, ExponentialFamily, ChiSquared)
