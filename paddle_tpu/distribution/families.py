"""Distribution-family tail (VERDICT r4 #7; reference:
python/paddle/distribution/ — beta.py, gamma.py, dirichlet.py,
multinomial.py, binomial.py, poisson.py, geometric.py, gumbel.py,
cauchy.py, student_t.py, multivariate_normal.py, independent.py,
transformed_distribution.py, continuous_bernoulli.py).

jax.random-backed sampling; log_prob/entropy as taped ops so the score
terms differentiate. KL pairs registered at the bottom."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..framework import random as _random
from ..framework.tensor import Tensor, apply_op
from . import (Distribution, Normal, Exponential, Laplace, Bernoulli,
               Categorical, register_kl, _arr)
from .transform import ChainTransform, Transform

__all__ = ["Beta", "Gamma", "Dirichlet", "Multinomial", "Binomial",
           "Poisson", "Geometric", "Gumbel", "Cauchy", "StudentT",
           "MultivariateNormal", "ContinuousBernoulli", "Independent",
           "TransformedDistribution", "ExponentialFamily", "ChiSquared"]


class ExponentialFamily(Distribution):
    """Marker base (reference: paddle.distribution.ExponentialFamily —
    enables the Bregman-divergence generic entropy; our families override
    entropy directly, so this is the classification hook only)."""


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(jax.random.beta(
            _random.op_key(), self.alpha, self.beta, shape))

    def log_prob(self, value):
        a, b = self.alpha, self.beta
        return apply_op(
            lambda v: (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
            - jsp.betaln(a, b), value)

    def entropy(self):
        a, b = self.alpha, self.beta
        return Tensor._wrap(
            jsp.betaln(a, b) - (a - 1) * jsp.digamma(a)
            - (b - 1) * jsp.digamma(b)
            + (a + b - 2) * jsp.digamma(a + b))

    @property
    def mean(self):
        return Tensor._wrap(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor._wrap(self.alpha * self.beta / (s * s * (s + 1)))


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(jax.random.gamma(
            _random.op_key(), self.concentration, shape) / self.rate)

    def log_prob(self, value):
        a, r = self.concentration, self.rate
        return apply_op(
            lambda v: a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
            - jsp.gammaln(a), value)

    def entropy(self):
        a, r = self.concentration, self.rate
        return Tensor._wrap(a - jnp.log(r) + jsp.gammaln(a)
                            + (1 - a) * jsp.digamma(a))

    @property
    def mean(self):
        return Tensor._wrap(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor._wrap(self.concentration / self.rate ** 2)


class ChiSquared(Gamma):
    def __init__(self, df):
        df = _arr(df)
        super().__init__(df / 2.0, jnp.full_like(df, 0.5))
        self.df = df


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(jax.random.dirichlet(
            _random.op_key(), self.concentration, shape))

    def log_prob(self, value):
        a = self.concentration
        norm = jnp.sum(jsp.gammaln(a), -1) - jsp.gammaln(jnp.sum(a, -1))
        return apply_op(
            lambda v: jnp.sum((a - 1) * jnp.log(v), -1) - norm, value)

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        lnB = jnp.sum(jsp.gammaln(a), -1) - jsp.gammaln(a0)
        return Tensor._wrap(
            lnB + (a0 - k) * jsp.digamma(a0)
            - jnp.sum((a - 1) * jsp.digamma(a), -1))

    @property
    def mean(self):
        return Tensor._wrap(
            self.concentration
            / jnp.sum(self.concentration, -1, keepdims=True))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_arr = _arr(probs)
        p = self.probs_arr / jnp.sum(self.probs_arr, -1, keepdims=True)
        self._p = p
        super().__init__(p.shape[:-1], p.shape[-1:])

    @property
    def probs(self):
        return Tensor._wrap(self._p)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        logits = jnp.log(jnp.clip(self._p, 1e-30))
        draws = jax.random.categorical(
            _random.op_key(), logits,
            shape=(self.total_count,) + shape)          # [n, ...]
        k = self._p.shape[-1]
        onehot = jax.nn.one_hot(draws, k, dtype=jnp.float32)
        return Tensor._wrap(jnp.sum(onehot, axis=0))

    def log_prob(self, value):
        logp = jnp.log(jnp.clip(self._p, 1e-30))
        n = self.total_count
        return apply_op(
            lambda v: jsp.gammaln(jnp.asarray(n + 1.0))
            - jnp.sum(jsp.gammaln(v + 1.0), -1)
            + jnp.sum(v * logp, -1), value)

    @property
    def mean(self):
        return Tensor._wrap(self.total_count * self._p)

    @property
    def variance(self):
        return Tensor._wrap(self.total_count * self._p * (1 - self._p))


class Binomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = _arr(total_count)
        self.probs_arr = _arr(probs)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.total_count), self.probs_arr.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(jax.random.binomial(
            _random.op_key(), self.total_count, self.probs_arr, shape))

    def log_prob(self, value):
        n, p = self.total_count, jnp.clip(self.probs_arr, 1e-7, 1 - 1e-7)
        return apply_op(
            lambda v: jsp.gammaln(n + 1.0) - jsp.gammaln(v + 1.0)
            - jsp.gammaln(n - v + 1.0) + v * jnp.log(p)
            + (n - v) * jnp.log1p(-p), value)

    @property
    def mean(self):
        return Tensor._wrap(self.total_count * self.probs_arr)

    @property
    def variance(self):
        return Tensor._wrap(self.total_count * self.probs_arr
                            * (1 - self.probs_arr))


class Poisson(ExponentialFamily):
    def __init__(self, rate):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(jax.random.poisson(
            _random.op_key(), self.rate, shape).astype(jnp.float32))

    def log_prob(self, value):
        r = self.rate
        return apply_op(
            lambda v: v * jnp.log(r) - r - jsp.gammaln(v + 1.0), value)

    @property
    def mean(self):
        return Tensor._wrap(self.rate)

    @property
    def variance(self):
        return Tensor._wrap(self.rate)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k in {0, 1, ...} (failures before the first
    success — the reference's support)."""

    def __init__(self, probs):
        self.probs_arr = _arr(probs)
        super().__init__(self.probs_arr.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_random.op_key(), shape, jnp.float32,
                               minval=1e-12)
        return Tensor._wrap(jnp.floor(
            jnp.log(u) / jnp.log1p(-self.probs_arr)))

    def log_prob(self, value):
        p = jnp.clip(self.probs_arr, 1e-7, 1 - 1e-7)
        return apply_op(lambda v: v * jnp.log1p(-p) + jnp.log(p), value)

    def entropy(self):
        p = jnp.clip(self.probs_arr, 1e-7, 1 - 1e-7)
        return Tensor._wrap(
            (-(1 - p) * jnp.log1p(-p) - p * jnp.log(p)) / p)

    @property
    def mean(self):
        return Tensor._wrap((1 - self.probs_arr) / self.probs_arr)


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(self.loc + self.scale * jax.random.gumbel(
            _random.op_key(), shape, jnp.float32))

    def log_prob(self, value):
        loc, sc = self.loc, self.scale
        return apply_op(
            lambda v: -(v - loc) / sc - jnp.exp(-(v - loc) / sc)
            - jnp.log(sc), value)

    def entropy(self):
        return Tensor._wrap(jnp.log(self.scale) + 1.0 + 0.57721566490153)

    @property
    def mean(self):
        return Tensor._wrap(self.loc + self.scale * 0.57721566490153)

    @property
    def variance(self):
        return Tensor._wrap((math.pi ** 2 / 6) * self.scale ** 2)


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(self.loc + self.scale * jax.random.cauchy(
            _random.op_key(), shape, jnp.float32))

    def log_prob(self, value):
        loc, sc = self.loc, self.scale
        return apply_op(
            lambda v: -jnp.log(math.pi * sc)
            - jnp.log1p(((v - loc) / sc) ** 2), value)

    def entropy(self):
        return Tensor._wrap(jnp.log(4 * math.pi * self.scale)
                            * jnp.ones(self.batch_shape))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = _arr(df)
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(self.loc + self.scale * jax.random.t(
            _random.op_key(), self.df, shape, jnp.float32))

    def log_prob(self, value):
        df, loc, sc = self.df, self.loc, self.scale
        return apply_op(
            lambda v: jsp.gammaln((df + 1) / 2) - jsp.gammaln(df / 2)
            - 0.5 * jnp.log(df * math.pi) - jnp.log(sc)
            - ((df + 1) / 2) * jnp.log1p(((v - loc) / sc) ** 2 / df),
            value)

    @property
    def mean(self):
        return Tensor._wrap(jnp.where(self.df > 1, self.loc, jnp.nan))


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None):
        self.loc = _arr(loc)
        if covariance_matrix is not None:
            self.cov = _arr(covariance_matrix)
            self.scale_tril = jnp.linalg.cholesky(self.cov)
        elif scale_tril is not None:
            self.scale_tril = _arr(scale_tril)
            self.cov = self.scale_tril @ jnp.swapaxes(
                self.scale_tril, -2, -1)
        else:
            raise ValueError("need covariance_matrix or scale_tril")
        super().__init__(self.loc.shape[:-1], self.loc.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor._wrap(jax.random.multivariate_normal(
            _random.op_key(), self.loc, self.cov, shape or None))

    def log_prob(self, value):
        loc, L = self.loc, self.scale_tril
        k = loc.shape[-1]
        logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), -1)

        def fn(v):
            diff = v - loc
            sol = jax.scipy.linalg.solve_triangular(L, diff[..., None],
                                                    lower=True)[..., 0]
            return (-0.5 * jnp.sum(sol ** 2, -1) - logdet
                    - 0.5 * k * math.log(2 * math.pi))

        return apply_op(fn, value)

    def entropy(self):
        k = self.loc.shape[-1]
        logdet = jnp.sum(jnp.log(jnp.diagonal(
            self.scale_tril, axis1=-2, axis2=-1)), -1)
        return Tensor._wrap(0.5 * k * (1 + math.log(2 * math.pi)) + logdet)

    @property
    def mean(self):
        return Tensor._wrap(self.loc)


class ContinuousBernoulli(Distribution):
    """Reference: paddle.distribution.ContinuousBernoulli (lam in (0,1);
    density C(lam) lam^x (1-lam)^(1-x) on [0, 1])."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs_arr = jnp.clip(_arr(probs), 1e-6, 1 - 1e-6)
        self.lims = lims
        super().__init__(self.probs_arr.shape)

    def _log_norm(self):
        lam = self.probs_arr
        # C(lam) = 2 atanh(1-2lam) / (1-2lam), -> 2 at lam=1/2
        near = (lam > self.lims[0]) & (lam < self.lims[1])
        safe = jnp.where(near, 0.25, lam)
        c = jnp.log(jnp.abs(2 * jnp.arctanh(1 - 2 * safe)
                            / (1 - 2 * safe)))
        return jnp.where(near, jnp.log(2.0), c)

    def log_prob(self, value):
        lam = self.probs_arr
        logc = self._log_norm()
        return apply_op(
            lambda v: logc + v * jnp.log(lam) + (1 - v) * jnp.log1p(-lam),
            value)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        lam = self.probs_arr
        u = jax.random.uniform(_random.op_key(), shape, jnp.float32,
                               minval=1e-7, maxval=1 - 1e-7)
        near = (lam > self.lims[0]) & (lam < self.lims[1])
        safe = jnp.where(near, 0.25, lam)
        x = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
             / (jnp.log(safe) - jnp.log1p(-safe)))
        return Tensor._wrap(jnp.where(near, u, x))

    @property
    def mean(self):
        lam = self.probs_arr
        near = (lam > self.lims[0]) & (lam < self.lims[1])
        safe = jnp.where(near, 0.25, lam)
        m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        return Tensor._wrap(jnp.where(near, 0.5, m))


class Independent(Distribution):
    """Reinterpret the rightmost ``reinterpreted_batch_ndims`` batch dims
    of ``base`` as event dims (log_prob sums over them)."""

    def __init__(self, base, reinterpreted_batch_ndims):
        self.base = base
        self.n = int(reinterpreted_batch_ndims)
        if self.n > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_ndims exceeds the base "
                             "distribution's batch rank")
        cut = len(base.batch_shape) - self.n
        super().__init__(base.batch_shape[:cut],
                         base.batch_shape[cut:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    rsample = sample

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return apply_op(
            lambda l: jnp.sum(l, axis=tuple(range(l.ndim - self.n,
                                                  l.ndim))), lp)

    def entropy(self):
        e = self.base.entropy()
        return apply_op(
            lambda l: jnp.sum(l, axis=tuple(range(l.ndim - self.n,
                                                  l.ndim))), e)


class TransformedDistribution(Distribution):
    """Push ``base`` through a chain of transforms (reference:
    paddle.distribution.TransformedDistribution — sample = T(base.sample),
    log_prob via the change-of-variables formula)."""

    def __init__(self, base, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.chain = ChainTransform(list(transforms))
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self.chain.forward(x)

    rsample = sample

    def log_prob(self, value):
        vt = value if isinstance(value, Tensor) else Tensor(value)
        x = self.chain.inverse(vt)
        base_lp = self.base.log_prob(x)
        fldj = self.chain.forward_log_det_jacobian(x)

        def combine(lp, ld):
            # the chain may have reduced event dims already (event-dim
            # transforms like StickBreaking return per-batch terms);
            # reduce only whatever trailing dims REMAIN beyond lp's rank
            # — never batch dims (code-review r5)
            if ld.ndim > lp.ndim:
                ld = jnp.sum(ld, axis=tuple(range(lp.ndim, ld.ndim)))
            return lp - ld

        return apply_op(combine, base_lp, fldj)


# ----------------------------------------------------------------- KL pairs


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    pa, pb, qa, qb = p.alpha, p.beta, q.alpha, q.beta
    ps = pa + pb
    return Tensor._wrap(
        jsp.betaln(qa, qb) - jsp.betaln(pa, pb)
        + (pa - qa) * jsp.digamma(pa) + (pb - qb) * jsp.digamma(pb)
        + (qa - pa + qb - pb) * jsp.digamma(ps))


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    pa, pr, qa, qr = p.concentration, p.rate, q.concentration, q.rate
    return Tensor._wrap(
        (pa - qa) * jsp.digamma(pa) - jsp.gammaln(pa) + jsp.gammaln(qa)
        + qa * (jnp.log(pr) - jnp.log(qr)) + pa * (qr - pr) / pr)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    pa, qa = p.concentration, q.concentration
    p0 = jnp.sum(pa, -1)
    return Tensor._wrap(
        jsp.gammaln(p0) - jnp.sum(jsp.gammaln(pa), -1)
        - jsp.gammaln(jnp.sum(qa, -1)) + jnp.sum(jsp.gammaln(qa), -1)
        + jnp.sum((pa - qa) * (jsp.digamma(pa)
                               - jsp.digamma(p0)[..., None]), -1))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    return Tensor._wrap(jnp.log(p.rate) - jnp.log(q.rate)
                        + q.rate / p.rate - 1.0)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    pp = jnp.clip(p.probs_arr, 1e-7, 1 - 1e-7)
    qp = jnp.clip(q.probs_arr, 1e-7, 1 - 1e-7)
    return Tensor._wrap(pp * (jnp.log(pp) - jnp.log(qp))
                        + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    pp = jnp.clip(p.probs_arr, 1e-7, 1 - 1e-7)
    qp = jnp.clip(q.probs_arr, 1e-7, 1 - 1e-7)
    return Tensor._wrap(
        jnp.log(pp) - jnp.log(qp)
        + (1 - pp) / pp * (jnp.log1p(-pp) - jnp.log1p(-qp)))


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return Tensor._wrap(p.rate * (jnp.log(p.rate) - jnp.log(q.rate))
                        - p.rate + q.rate)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    return Tensor._wrap(
        jnp.log(q.scale) - jnp.log(p.scale)
        + d / q.scale
        + p.scale / q.scale * jnp.exp(-d / p.scale) - 1.0)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    k = p.loc.shape[-1]
    qinv = jnp.linalg.inv(q.cov)
    diff = q.loc - p.loc
    tr = jnp.trace(qinv @ p.cov, axis1=-2, axis2=-1)
    maha = jnp.einsum("...i,...ij,...j->...", diff, qinv, diff)
    logdet = (jnp.linalg.slogdet(q.cov)[1]
              - jnp.linalg.slogdet(p.cov)[1])
    return Tensor._wrap(0.5 * (tr + maha - k + logdet))
