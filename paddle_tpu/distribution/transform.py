"""paddle.distribution.transform parity (reference:
python/paddle/distribution/transform.py — Transform base +
Abs/Affine/Chain/Exp/Power/Reshape/Sigmoid/Softmax/Stack/StickBreaking/
Tanh transforms used by TransformedDistribution).

Each transform exposes forward / inverse / forward_log_det_jacobian over
Tensors (taped, so reparameterized sampling stays differentiable)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op

__all__ = ["Transform", "AbsTransform", "AffineTransform",
           "ChainTransform", "ExpTransform", "PowerTransform",
           "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
           "StackTransform", "StickBreakingTransform", "TanhTransform",
           "IndependentTransform"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class Transform:
    """Bijector base. ``_forward``/``_inverse``/``_fldj`` work on raw
    arrays; the public methods wrap them as taped ops."""

    # event dims consumed by one application (0 = elementwise)
    _event_dim = 0

    def forward(self, x):
        return apply_op(self._forward, x if isinstance(x, Tensor)
                        else Tensor(x))

    def inverse(self, y):
        return apply_op(self._inverse, y if isinstance(y, Tensor)
                        else Tensor(y))

    def forward_log_det_jacobian(self, x):
        return apply_op(self._fldj, x if isinstance(x, Tensor)
                        else Tensor(x))

    def inverse_log_det_jacobian(self, y):
        return apply_op(
            lambda yd: -self._fldj(self._inverse(yd)),
            y if isinstance(y, Tensor) else Tensor(y))

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    """y = |x| (not bijective; inverse returns the positive branch)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh(x)^2) = 2*(log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (not a bijection on R^n; the
    reference pairs it with a reference measure on the simplex)."""
    _event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError(
            "SoftmaxTransform has no log-det (dimension-reducing); "
            "the reference raises here too")


class StickBreakingTransform(Transform):
    """R^{n} -> interior of the n-simplex (n+1 coordinates summing to 1)
    via iterated sigmoids — the reference's simplex bijector."""
    _event_dim = 1

    def _forward(self, x):
        n = x.shape[-1]
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zpad = jnp.concatenate([z, jnp.ones(x.shape[:-1] + (1,), x.dtype)],
                               -1)
        cum = jnp.cumprod(1 - z, axis=-1)
        cumpad = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), cum], -1)
        return zpad * cumpad

    def _inverse(self, y):
        n = y.shape[-1] - 1
        cum = jnp.cumsum(y[..., :-1], axis=-1)
        rest = 1.0 - jnp.concatenate(
            [jnp.zeros(y.shape[:-1] + (1,), y.dtype), cum[..., :-1]], -1)
        z = y[..., :-1] / rest
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _fldj(self, x):
        n = x.shape[-1]
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=x.dtype))
        t = x - offset
        z = jax.nn.sigmoid(t)
        cum = jnp.cumprod(1 - z, axis=-1)
        cumpad = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), cum[..., :-1]], -1)
        # d y_i / d x_i = sigmoid'(t_i) * prod_{j<i}(1-z_j)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(cumpad), -1)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._event_dim = len(self.in_event_shape)

    def _forward(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(lead + self.out_event_shape)

    def _inverse(self, y):
        lead = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(lead + self.in_event_shape)

    def _fldj(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(lead, x.dtype)


class StackTransform(Transform):
    """Apply the i-th transform to the i-th slice along ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, method, x):
        parts = []
        n = x.shape[self.axis]
        for i in range(n):
            sl = jnp.take(x, i, axis=self.axis)
            parts.append(getattr(self.transforms[i], method)(sl))
        return jnp.stack(parts, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _fldj(self, x):
        return self._map("_fldj", x)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._event_dim = max(
            (t._event_dim for t in self.transforms), default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = None
        for t in self.transforms:
            term = t._fldj(x)
            # reduce elementwise terms over event dims the chain treats as
            # a single event
            while term.ndim > 0 and self._event_dim > t._event_dim and (
                    term.ndim >= self._event_dim - t._event_dim):
                term = jnp.sum(
                    term, axis=tuple(range(
                        term.ndim - (self._event_dim - t._event_dim),
                        term.ndim)))
                break
            total = term if total is None else total + term
            x = t._forward(x)
        return total


class IndependentTransform(Transform):
    """Reinterpret ``n`` batch dims of ``base`` as event dims (the log-det
    sums over them)."""

    def __init__(self, base, reinterpreted_batch_ndims):
        self.base = base
        self.n = int(reinterpreted_batch_ndims)
        self._event_dim = base._event_dim + self.n

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        term = self.base._fldj(x)
        return jnp.sum(term, axis=tuple(range(term.ndim - self.n,
                                              term.ndim)))
