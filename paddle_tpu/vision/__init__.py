"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from . import datasets, models, transforms  # noqa: F401
from .datasets import DatasetFolder, FakeData, ImageFolder  # noqa: F401
