"""DenseNet (reference: python/paddle/vision/models/densenet.py)."""
from ... import nn

__all__ = ["DenseNet", "densenet121"]


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth, bn_size):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)

    def forward(self, x):
        from ...nn import functional as F
        import paddle_tpu as paddle

        y = self.conv1(F.relu(self.norm1(x)))
        y = self.conv2(F.relu(self.norm2(y)))
        return paddle.concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_ch)
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, 2)

    def forward(self, x):
        from ...nn import functional as F

        return self.pool(self.conv(F.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=(6, 12, 24, 16), growth=32, bn_size=4,
                 num_classes=1000, num_init_features=64):
        super().__init__()
        self.num_classes = num_classes
        feats = [
            nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init_features), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
        ]
        ch = num_init_features
        for i, n in enumerate(layers):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size))
                ch += growth
            if i != len(layers) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def densenet121(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return DenseNet((6, 12, 24, 16), **kwargs)
