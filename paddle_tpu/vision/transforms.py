"""Image transforms (reference: python/paddle/vision/transforms/).

Host-side numpy/PIL ops; CHW float32 output feeds DataLoader collate.
"""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "BrightnessTransform", "to_tensor", "normalize",
    "resize", "hflip", "vflip", "center_crop", "crop", "pad",
]


def _to_numpy(img):
    if isinstance(img, np.ndarray):
        return img
    # PIL image
    return np.asarray(img)


def _size_pair(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


def to_tensor(img, data_format="CHW"):
    a = _to_numpy(img)
    if a.ndim == 2:
        a = a[:, :, None]
    if a.dtype == np.uint8:
        a = a.astype(np.float32) / 255.0
    else:
        a = a.astype(np.float32)
    if data_format == "CHW":
        a = np.transpose(a, (2, 0, 1))
    return a


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        return (a - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    return (a - mean) / std


def resize(img, size, interpolation="bilinear"):
    a = _to_numpy(img)
    h, w = a.shape[:2]
    if isinstance(size, numbers.Number):
        # shorter side -> size, keep aspect
        if h < w:
            oh, ow = int(size), int(size * w / h)
        else:
            oh, ow = int(size * h / w), int(size)
    else:
        oh, ow = _size_pair(size)
    if (oh, ow) == (h, w):
        return a
    # vectorized bilinear on numpy (no PIL dependency at runtime)
    ys = np.linspace(0, h - 1, oh, dtype=np.float32)
    xs = np.linspace(0, w - 1, ow, dtype=np.float32)
    y0 = np.floor(ys).astype(np.int32)
    x0 = np.floor(xs).astype(np.int32)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if a.ndim == 2:
        a = a[:, :, None]
    a = a.astype(np.float32)
    top = a[y0][:, x0] * (1 - wx[..., None]) + a[y0][:, x1] * wx[..., None]
    bot = a[y1][:, x0] * (1 - wx[..., None]) + a[y1][:, x1] * wx[..., None]
    out = top * (1 - wy[..., None]) + bot * wy[..., None]
    return out


def hflip(img):
    return _to_numpy(img)[:, ::-1]


def vflip(img):
    return _to_numpy(img)[::-1]


def crop(img, top, left, height, width):
    return _to_numpy(img)[top : top + height, left : left + width]


def center_crop(img, output_size):
    a = _to_numpy(img)
    th, tw = _size_pair(output_size)
    h, w = a.shape[:2]
    i = max(0, (h - th) // 2)
    j = max(0, (w - tw) // 2)
    return crop(a, i, j, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    a = _to_numpy(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    pads = [(pt, pb), (pl, pr)] + [(0, 0)] * (a.ndim - 2)
    if padding_mode == "constant":
        return np.pad(a, pads, constant_values=fill)
    return np.pad(a, pads, mode=padding_mode)


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = mean if not isinstance(mean, numbers.Number) else [mean] * 3
        self.std = std if not isinstance(std, numbers.Number) else [std] * 3
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0):
        self.size = _size_pair(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def _apply_image(self, img):
        a = _to_numpy(img)
        if self.padding is not None:
            a = pad(a, self.padding, self.fill)
        th, tw = self.size
        h, w = a.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            a = pad(a, (max(0, tw - w), max(0, th - h)), self.fill)
            h, w = a.shape[:2]
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return crop(a, i, j, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = _size_pair(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        a = _to_numpy(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            aspect = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return resize(crop(a, i, j, ch, cw), self.size, self.interpolation)
        return resize(center_crop(a, min(h, w)), self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _to_numpy(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _to_numpy(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(_to_numpy(img), self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        a = _to_numpy(img).astype(np.float32)
        factor = 1 + random.uniform(-self.value, self.value)
        return np.clip(a * factor, 0, 255 if a.max() > 1 else 1.0)
