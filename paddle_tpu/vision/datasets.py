"""Vision datasets (reference: python/paddle/vision/datasets/).

No network in this environment: datasets read local files only
(DatasetFolder) or generate synthetic data (FakeData for harnesses).
"""
from __future__ import annotations

import os
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    """ImageNet-style root/class_x/img.ext layout (reference:
    python/paddle/vision/datasets/folder.py)."""

    def __init__(self, root, loader: Optional[Callable] = None,
                 extensions=IMG_EXTENSIONS, transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    path = os.path.join(dirpath, f)
                    ok = (is_valid_file(path) if is_valid_file
                          else f.lower().endswith(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image

            with Image.open(path) as img:
                return np.asarray(img.convert("RGB"))
        except ImportError as e:
            raise RuntimeError("PIL unavailable; use .npy files or a custom loader") from e

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


class FakeData(Dataset):
    """Synthetic image dataset for harnesses/benchmarks (deterministic)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=1000,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, index):
        rng = np.random.default_rng(self.seed + index)
        img = rng.standard_normal(self.image_shape).astype(np.float32)
        label = int(rng.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size
