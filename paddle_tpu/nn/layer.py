"""nn.Layer: the module system (reference: python/paddle/nn/layer/layers.py).

Stateful module tree with named parameters/buffers/sublayers, forward hooks,
state_dict — the Paddle-shaped shell. The functional core extracts the
parameter pytree (``paddle_tpu.jit.functional_call``) so the whole training
step can be one compiled XLA program; eager calls run op-by-op through the
autograd tape.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Callable, Iterator, Optional, Tuple

import jax
import numpy as np

from ..framework import dtypes, random as _random
from ..framework.tensor import Parameter, Tensor
from . import initializer as I

__all__ = ["Layer", "LayerList", "Sequential", "ParameterList"]


class _HookRemoveHelper:
    def __init__(self, hooks, hid):
        self._hooks, self._hid = hooks, hid

    def remove(self):
        self._hooks.pop(self._hid, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        self._dtype = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        self._full_name = name_scope or type(self).__name__.lower()
        self.training = True
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_id = 0

    # ------------------------------------------------------------------ attrs
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        sublayers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            sublayers.pop(name, None) if sublayers else None
        elif isinstance(value, Layer):
            if sublayers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            sublayers[name] = value
        elif params is not None and name in params:
            if value is None:
                params.pop(name)
            else:
                params[name] = value
            # keep the instance __dict__ fast path coherent with _parameters
            self.__dict__.pop(name, None)
            if value is not None:
                object.__setattr__(self, name, value)
            return
        elif buffers is not None and name in buffers:
            buffers[name] = value
            self.__dict__.pop(name, None)
            if value is not None:
                object.__setattr__(self, name, value)
            return
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        found = False
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                found = True
        # the instance __dict__ fast-path copy must go too, else the
        # attribute stays reachable after deletion
        if name in self.__dict__:
            object.__delattr__(self, name)
        elif not found:
            object.__delattr__(self, name)

    # ------------------------------------------------------------- parameters
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer: Optional[I.Initializer] = None,
    ) -> Parameter:
        dtype = dtypes.convert_dtype(dtype) or self._dtype
        init = default_initializer
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        name = getattr(attr, "name", None) if attr is not None else None
        key = _param_key(self._full_name, name or ("b" if is_bias else "w"), shape)
        p = Parameter(init(tuple(int(s) for s in shape), dtype, key), name=name)
        if attr is not None and getattr(attr, "learning_rate", None) is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
        if attr is not None and getattr(attr, "trainable", True) is False:
            p.stop_gradient = True
            p.trainable = False
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if tensor is not None:
            tensor.name = name

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer, lp in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{lp}.{pname}" if lp else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer, lp in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{lp}.{bname}" if lp else bname), b

    def _walk(self, prefix="", include_sublayers=True):
        yield "", self, prefix
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sp = f"{prefix}.{name}" if prefix else name
                yield from sub._walk(sp, True)

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for _, sub, _ in self._walk("", True):
            if sub is not self:
                out.append(sub)
        return out

    def named_sublayers(self, prefix="", include_self=False):
        for name, sub, lp in self._walk(prefix, True):
            if sub is self and not include_self:
                continue
            yield lp, sub

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -------------------------------------------------------------- state IO
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix=""):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix, include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix, include_sublayers=include_sublayers):
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name in own:
                own[name].set_value(value)
            else:
                unexpected.append(name)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------------ modes
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # ---------------------------------------------------------------- dtypes
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = dtypes.convert_dtype(dtype)
            for _, p in self.named_parameters():
                if dtypes.is_floating_point(p.dtype):
                    p._data = p._data.astype(dt)
            for _, b in self.named_buffers():
                if b is not None and dtypes.is_floating_point(b.dtype):
                    b._data = b._data.astype(dt)
            for layer in self.sublayers(include_self=True):
                layer._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def float(self):
        return self.to(dtype="float32")

    # ----------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return _HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return _HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ------------------------------------------------------------------- call
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}"]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"


def _param_key(scope: str, name: str, shape) -> jax.Array:
    """Deterministic per-parameter PRNG key: fold a stable hash of the
    (scope, name, shape) identity into the global base key. Replaces the
    reference's rank-0 init + broadcast (fleet/utils hybrid_parallel_util
    broadcast_*_parameters) — every process computes identical inits."""
    ident = f"{scope}/{name}/{tuple(shape)}".encode()
    h = int.from_bytes(hashlib.sha256(ident).digest()[:4], "little")
    return jax.random.fold_in(_random.base_key(), h)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx % len(self._sub_layers) if idx < 0 else idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and not isinstance(layers[0], Layer):
            layers = layers[0]
        for i, l in enumerate(layers):
            if isinstance(l, (list, tuple)):
                name, l = l
            else:
                name = str(i)
            self.add_sublayer(name, l)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())
