"""Common layers: Linear, Embedding, Dropout, etc.
(reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import math

from ..framework.param_attr import ParamAttr
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = ["Linear", "Embedding", "Dropout", "Dropout2D", "Flatten", "Identity",
           "Pad2D", "Upsample", "UpsamplingBilinear2D", "CosineSimilarity", "Bilinear"]


class Linear(Layer):
    """y = xW + b, weight [in_features, out_features] (paddle layout;
    reference: python/paddle/nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        weight_attr = ParamAttr._to_attr(weight_attr)
        bias_attr = ParamAttr._to_attr(bias_attr)
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=None if (weight_attr and weight_attr.initializer) else I.XavierNormal(),
        )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        weight_attr = ParamAttr._to_attr(weight_attr)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=None if (weight_attr and weight_attr.initializer) else I.Normal(0.0, 1.0),
        )

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=[0, 1], training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value, data_format=self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size=size, scale_factor=scale_factor, mode="bilinear", align_corners=True)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None):
        super().__init__()
        bound = 1.0 / math.sqrt(in1_features)
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features),
            default_initializer=I.Uniform(-bound, bound),
        )
        self.bias = self.create_parameter((1, out_features), is_bias=True)

    def forward(self, x1, x2):
        from ..framework.tensor import apply_op
        import jax.numpy as jnp

        return apply_op(
            lambda a, b, w, bias: jnp.einsum("bi,oij,bj->bo", a, w, b) + bias,
            x1, x2, self.weight, self.bias,
        )
