"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py —
RNNCellBase, SimpleRNNCell/LSTMCell/GRUCell, the RNN sequence wrapper and
the SimpleRNN/LSTM/GRU multi-layer networks).

TPU design: the time loop is a ``lax.scan`` inside one ``apply_op``, so a
whole sequence (or a whole stacked bidirectional network) traces to a
single XLA program — per-step Python dispatch would be the exact dygraph
overhead this framework exists to erase, and scan keeps the compiled
control flow static for jit. Gate conventions match the reference (which
match cuDNN/torch): LSTM chunks [i, f, g(c~), o]; GRU chunks [r, z, c~]
with ``h' = z*h + (1-z)*c~`` and the reset gate applied to the hidden
projection of the candidate."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, apply_op
from . import functional as F
from .layer import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class RNNCellBase(Layer):
    """Base: parameter creation + default initial states."""

    def _create(self, hidden_size, input_size, gates):
        k = 1.0 / math.sqrt(hidden_size)
        from .initializer import Uniform

        init = Uniform(-k, k)
        self.weight_ih = self.create_parameter(
            (gates * hidden_size, input_size), default_initializer=init)
        self.weight_hh = self.create_parameter(
            (gates * hidden_size, hidden_size), default_initializer=init)
        self.bias_ih = self.create_parameter(
            (gates * hidden_size,), is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter(
            (gates * hidden_size,), is_bias=True, default_initializer=init)

    def get_initial_states(self, batch, dtype=jnp.float32):
        shape = (batch, self.hidden_size)
        if getattr(self, "state_is_tuple", False):
            return (Tensor._wrap(jnp.zeros(shape, dtype)),
                    Tensor._wrap(jnp.zeros(shape, dtype)))
        return Tensor._wrap(jnp.zeros(shape, dtype))

class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh); act in tanh/relu."""

    state_is_tuple = False

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError("SimpleRNNCell activation: tanh | relu")
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        self._create(hidden_size, input_size, 1)

    @staticmethod
    def _step(x, h, wih, whh, bih, bhh, activation="tanh"):
        pre = x @ wih.T + bih + h @ whh.T + bhh
        return jnp.tanh(pre) if activation == "tanh" else jax.nn.relu(pre)

    def forward(self, inputs, states=None):
        h = (states if states is not None
             else self.get_initial_states(_arr(inputs).shape[0]))

        def fn(x, hh, wih, whh, bih, bhh):
            return self._step(x, hh, wih, whh, bih, bhh, self.activation)

        out = apply_op(fn, inputs, h, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh)
        return out, out


class LSTMCell(RNNCellBase):
    """Gate chunks [i, f, g, o] (the reference/cuDNN order)."""

    state_is_tuple = True

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self._create(hidden_size, input_size, 4)

    @staticmethod
    def _step(x, h, c, wih, whh, bih, bhh):
        hs = h.shape[-1]
        pre = x @ wih.T + bih + h @ whh.T + bhh
        i = jax.nn.sigmoid(pre[..., 0 * hs:1 * hs])
        f = jax.nn.sigmoid(pre[..., 1 * hs:2 * hs])
        g = jnp.tanh(pre[..., 2 * hs:3 * hs])
        o = jax.nn.sigmoid(pre[..., 3 * hs:4 * hs])
        c2 = f * c + i * g
        return o * jnp.tanh(c2), c2

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(_arr(inputs).shape[0])
        h, c = states

        def fn(x, hh, cc, wih, whh, bih, bhh):
            return jnp.stack(self._step(x, hh, cc, wih, whh, bih, bhh))

        both = apply_op(fn, inputs, h, c, self.weight_ih, self.weight_hh,
                        self.bias_ih, self.bias_hh)
        h2 = apply_op(lambda b: b[0], both)
        c2 = apply_op(lambda b: b[1], both)
        return h2, (h2, c2)


class GRUCell(RNNCellBase):
    """Gate chunks [r, z, c~]; h' = z*h + (1-z)*c~ with the reset gate on
    the candidate's hidden projection (the reference formulation)."""

    state_is_tuple = False

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self._create(hidden_size, input_size, 3)

    @staticmethod
    def _step(x, h, wih, whh, bih, bhh):
        hs = h.shape[-1]
        gi = x @ wih.T + bih
        gh = h @ whh.T + bhh
        r = jax.nn.sigmoid(gi[..., :hs] + gh[..., :hs])
        z = jax.nn.sigmoid(gi[..., hs:2 * hs] + gh[..., hs:2 * hs])
        cand = jnp.tanh(gi[..., 2 * hs:] + r * gh[..., 2 * hs:])
        return z * h + (1.0 - z) * cand

    def forward(self, inputs, states=None):
        h = (states if states is not None
             else self.get_initial_states(_arr(inputs).shape[0]))
        out = apply_op(self._step, inputs, h, self.weight_ih,
                       self.weight_hh, self.bias_ih, self.bias_hh)
        return out, out


def _scan_layer_params(cell, xs, h0, reverse, params):
    """One direction of one layer as a single lax.scan over time.
    ``xs``: [T, B, I] raw array; states are raw arrays/tuples; params
    are traced operands so weight gradients flow through apply_op."""
    wih, whh, bih, bhh = params
    if isinstance(cell, LSTMCell):
        def body(carry, x):
            h, c = carry
            h2, c2 = LSTMCell._step(x, h, c, wih, whh, bih, bhh)
            return (h2, c2), h2
    elif isinstance(cell, GRUCell):
        def body(carry, x):
            h2 = GRUCell._step(x, carry, wih, whh, bih, bhh)
            return h2, h2
    else:
        act = cell.activation

        def body(carry, x):
            h2 = SimpleRNNCell._step(x, carry, wih, whh, bih, bhh, act)
            return h2, h2

    final, ys = jax.lax.scan(body, h0, xs, reverse=reverse)
    return ys, final


class RNN(Layer):
    """Run ``cell`` over a sequence with one compiled scan (reference:
    paddle.nn.RNN(cell, is_reverse, time_major))."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = bool(is_reverse)
        self.time_major = bool(time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if sequence_length is not None:
            raise NotImplementedError(
                "RNN: ragged sequence_length not supported; pad + mask")
        cell = self.cell
        tm = self.time_major
        rev = self.is_reverse
        batch_axis = 0 if tm else 1

        if initial_states is None:
            b = _arr(inputs).shape[1 if tm else 0]
            initial_states = cell.get_initial_states(b)
        tup = isinstance(initial_states, (tuple, list))
        state_args = list(initial_states) if tup else [initial_states]
        wts = [cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh]

        def fn(x, *rest):
            states, (wih, whh, bih, bhh) = rest[:-4], rest[-4:]
            xs = x if tm else jnp.swapaxes(x, 0, 1)
            h0 = tuple(states) if tup else states[0]
            ys, final = _scan_layer_params(
                cell, xs, h0, rev, (wih, whh, bih, bhh))
            if not tm:
                ys = jnp.swapaxes(ys, 0, 1)
            if tup:
                return (ys,) + tuple(final)
            return ys, final

        outs = apply_op(fn, inputs, *state_args, *wts)
        if tup:
            return outs[0], (outs[1], outs[2])
        return outs[0], outs[1]


class BiRNN(Layer):
    """Forward + backward cells over the same input, outputs concatenated
    (reference: paddle.nn.BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_f, st_b = (initial_states if initial_states is not None
                      else (None, None))
        y_f, s_f = self.rnn_fw(inputs, st_f, sequence_length)
        y_b, s_b = self.rnn_bw(inputs, st_b, sequence_length)
        y = apply_op(lambda a, b: jnp.concatenate([a, b], -1), y_f, y_b)
        return y, (s_f, s_b)


class _RNNBase(Layer):
    """Stacked (optionally bidirectional) recurrent network."""

    _CELL = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, **kw):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError("direction: forward | bidirect")
        self.bidirect = direction != "forward"
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = float(dropout)
        self.hidden_size = hidden_size
        from .layer import LayerList

        mk = (lambda i: self._cell(i, hidden_size, activation))
        widths = [input_size] + [
            hidden_size * (2 if self.bidirect else 1)] * (num_layers - 1)
        self.fw = LayerList([mk(w) for w in widths])
        self.bw = (LayerList([mk(w) for w in widths])
                   if self.bidirect else None)

    def _cell(self, inp, hid, activation):
        if activation is not None:
            return type(self)._CELL(inp, hid, activation=activation)
        return type(self)._CELL(inp, hid)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if sequence_length is not None:
            raise NotImplementedError(
                "ragged sequence_length not supported; pad + mask")
        y = inputs
        finals = []
        for li in range(self.num_layers):
            if self.bidirect:
                layer = BiRNN(self.fw[li], self.bw[li],
                              time_major=self.time_major)
                y, (s_f, s_b) = layer(y)
                finals.append((s_f, s_b))
            else:
                layer = RNN(self.fw[li], time_major=self.time_major)
                y, s = layer(y)
                finals.append(s)
            if self.dropout and self.training and li < self.num_layers - 1:
                y = F.dropout(y, p=self.dropout, training=True)
        return y, finals


class SimpleRNN(_RNNBase):
    _CELL = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation)


class LSTM(_RNNBase):
    _CELL = LSTMCell


class GRU(_RNNBase):
    _CELL = GRUCell


class BeamSearchDecoder(Layer):
    """Beam-search decoding over an RNN cell (reference:
    paddle.nn.BeamSearchDecoder + paddle.nn.dynamic_decode).

    Host-driven eager loop (the legacy seq2seq API surface — the modern
    generation path is models/generation.py's compiled scan): each step
    embeds the live tokens, advances the cell for every (batch, beam)
    hypothesis, applies ``output_fn`` for vocab logits, and keeps the
    top ``beam_size`` continuations by cumulative log-prob. Finished
    beams (end_token) are frozen with a one-hot distribution so their
    scores stop changing."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        super().__init__()
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _logits(self, cell_out):
        out = (self.output_fn(cell_out) if self.output_fn is not None
               else cell_out)
        return jax.nn.log_softmax(_arr(out).astype(jnp.float32), -1)

    def decode(self, initial_states=None, batch=1, max_step_num=32):
        """Returns (token ids [batch, beam, T], scores [batch, beam])."""
        bs, k = batch, self.beam_size
        tup = getattr(self.cell, "state_is_tuple", False)

        def tile(s):
            a = _arr(s)
            return jnp.repeat(a, k, axis=0)  # [bs*k, H]

        if initial_states is None:
            initial_states = self.cell.get_initial_states(bs)
        states = (tuple(tile(s) for s in initial_states) if tup
                  else tile(initial_states))
        tokens = np.full((bs, k), self.start_token, np.int64)
        # beam 0 starts live, others at -inf so step 1 fans from one beam
        scores = np.full((bs, k), -1e9, np.float32)
        scores[:, 0] = 0.0
        finished = np.zeros((bs, k), bool)
        history = []
        for _ in range(max_step_num):
            if finished.all():
                break
            tok_t = Tensor._wrap(jnp.asarray(tokens.reshape(-1)))
            emb = (self.embedding_fn(tok_t) if self.embedding_fn
                   else Tensor._wrap(jax.nn.one_hot(
                       _arr(tok_t), self.cell.input_size,
                       dtype=jnp.float32)))
            st_in = (tuple(Tensor._wrap(s) for s in states) if tup
                     else Tensor._wrap(states))
            out, new_states = self.cell(emb, st_in)
            logp = np.asarray(self._logits(out)).reshape(bs, k, -1)
            v = logp.shape[-1]
            # frozen finished beams: only end_token continues, at 0 cost
            # (an end_token outside the vocab means "never finishes" —
            # e.g. a fixed-length decode — and nothing to freeze)
            if 0 <= self.end_token < v:
                frozen = np.full((bs, k, v), -1e9, np.float32)
                frozen[:, :, self.end_token] = 0.0
                logp = np.where(finished[:, :, None], frozen, logp)
            total = scores[:, :, None] + logp  # [bs, k, v]
            flat = total.reshape(bs, -1)
            top = np.argsort(-flat, axis=-1)[:, :k]
            scores = np.take_along_axis(flat, top, -1)
            beam_src = top // v
            tokens = (top % v).astype(np.int64)
            finished = np.take_along_axis(finished, beam_src, 1) | (
                tokens == self.end_token)
            # reorder states + history by the source beam of each winner
            gather = (beam_src + np.arange(bs)[:, None] * k).reshape(-1)
            g = jnp.asarray(gather)

            def pick(s):
                return _arr(s)[g]

            states = (tuple(pick(s) for s in new_states) if tup
                      else pick(new_states))
            history = [h[np.arange(bs)[:, None], beam_src]
                       for h in history]
            history.append(tokens.copy())
        ids = np.stack(history, axis=-1) if history else np.zeros(
            (bs, k, 0), np.int64)
        return ids, scores

    def forward(self, initial_states=None, batch=1, max_step_num=32):
        return self.decode(initial_states, batch, max_step_num)


def dynamic_decode(decoder, inits=None, max_step_num=32, batch=1, **kw):
    """Reference: paddle.nn.dynamic_decode(decoder, inits, max_step_num)."""
    return decoder.decode(inits, batch=batch, max_step_num=max_step_num)


__all__.extend(["BeamSearchDecoder", "dynamic_decode"])
