"""Conv layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

from ..framework.param_attr import ParamAttr
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose"]


def _ntuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1, padding=0,
                 dilation=1, groups=1, weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        self.in_channels, self.out_channels = in_channels, out_channels
        self.kernel_size = _ntuple(kernel_size, nd)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.groups = groups
        self.data_format = data_format
        weight_attr = ParamAttr._to_attr(weight_attr)
        bias_attr = ParamAttr._to_attr(bias_attr)
        w_shape = (out_channels, in_channels // groups) + self.kernel_size
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=None if (weight_attr and weight_attr.initializer) else I.KaimingNormal(),
        )
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, kernel_size={self.kernel_size}, "
                f"stride={self.stride}, padding={self.padding}")


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, stride=self.stride,
                        padding=self.padding, dilation=self.dilation,
                        groups=self.groups, data_format=self.data_format)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, stride=self.stride,
                        padding=self.padding, dilation=self.dilation,
                        groups=self.groups, data_format=self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, stride=self.stride,
                        padding=self.padding, dilation=self.dilation,
                        groups=self.groups, data_format=self.data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.output_padding = output_padding
        k = _ntuple(kernel_size, 2)
        weight_attr = ParamAttr._to_attr(weight_attr)
        bias_attr = ParamAttr._to_attr(bias_attr)
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups) + k, attr=weight_attr,
            default_initializer=None if (weight_attr and weight_attr.initializer) else I.KaimingNormal(),
        )
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, stride=self.stride,
                                  padding=self.padding, output_padding=self.output_padding,
                                  dilation=self.dilation, groups=self.groups,
                                  output_size=output_size)
