"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ..framework.param_attr import ParamAttr
from ..framework.tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "LayerNorm", "GroupNorm", "InstanceNorm2D", "RMSNorm", "SyncBatchNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum, self.epsilon = momentum, epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        weight_attr = ParamAttr._to_attr(weight_attr)
        bias_attr = ParamAttr._to_attr(bias_attr)
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format, use_global_stats=self.use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}, epsilon={self.epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Under SPMD compilation batch stats are computed over the global batch
    automatically (the mean/var reductions become cross-replica when the
    batch axis is sharded), so SyncBatchNorm == BatchNorm here. Kept for API
    parity (reference: python/paddle/nn/layer/norm.py SyncBatchNorm over
    sync_batch_norm_op CUDA+NCCL kernel).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        weight_attr = ParamAttr._to_attr(weight_attr)
        bias_attr = ParamAttr._to_attr(bias_attr)
        self.weight = None if weight_attr is False else self.create_parameter(
            self.normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """(reference capability: rms_norm fusion kernel, Paddle 2.6)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups, self.epsilon = num_groups, epsilon
        weight_attr = ParamAttr._to_attr(weight_attr)
        bias_attr = ParamAttr._to_attr(bias_attr)
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias, self.epsilon)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_features, self.epsilon = num_features, epsilon
        self.weight = self.create_parameter((num_features,), default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_features, self.weight, self.bias, self.epsilon)
