"""paddle_tpu.nn — the Layer system + layer library (reference: python/paddle/nn/)."""
from . import functional, initializer, quant
from .activation import *  # noqa: F401,F403
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .layer import Layer, LayerList, ParameterList, Sequential
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .transformer import (
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)

__all__ = [n for n in dir() if not n.startswith("_")]
