"""Weight-only quantization for the decode path.

Reference capability: ``paddle.nn.quant.weight_quantize`` /
``weight_only_linear`` backing ``fused_multi_transformer_int8_op.cu``
(SURVEY A3.x) — small-batch decode is weight-bandwidth-bound, so int8
weights halve the dominant HBM traffic. TPU design: weights are STORED
int8 with one f32 scale per output channel (symmetric); the matmul runs
``x @ convert(W_int8)`` — XLA fuses the convert into the dot's operand
load, so only int8 bytes cross HBM — and the per-channel scale multiplies
the f32/bf16 output. No custom kernel needed; the bandwidth win is the
storage dtype.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from .layer import Layer

__all__ = ["weight_quantize", "weight_only_linear", "WeightOnlyLinear",
           "quantize_for_decode"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor._wrap(jnp.asarray(x))


def weight_quantize(x, algo="weight_only_int8"):
    """Per-output-channel symmetric int8 quantization of a [in, out] weight.
    Returns ``(int8 weight [in, out], f32 scales [out])``."""
    if algo != "weight_only_int8":
        raise NotImplementedError(
            f"weight_quantize: only 'weight_only_int8' is supported "
            f"(got {algo!r}); int4 is a recorded gap")
    w = _t(x)._data
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scales = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scales[None, :]),
                 -127, 127).astype(jnp.int8)
    return Tensor._wrap(q), Tensor._wrap(scales)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8"):
    """y = x @ dequant(W) + b with int8-stored W (reference:
    paddle.nn.quant.weight_only_linear)."""
    if weight_dtype != "int8":
        raise NotImplementedError("weight_only_linear: int8 only")
    args = [_t(x), _t(weight), _t(weight_scale)]
    has_bias = bias is not None
    if has_bias:
        args.append(_t(bias))

    def fn(xa, wq, sc, *b):
        y = jnp.dot(xa, wq.astype(xa.dtype),
                    preferred_element_type=jnp.float32)
        y = (y * sc.astype(jnp.float32)).astype(xa.dtype)
        if b:
            y = y + b[0].astype(xa.dtype)
        return y

    return apply_op(fn, *args)


class WeightOnlyLinear(Layer):
    """Drop-in decode-path replacement for nn.Linear with an int8 weight.

    Int8 weight and scales are registered as buffers (not parameters): a
    quantized model serves, it does not train.
    """

    def __init__(self, linear):
        super().__init__()
        self.in_features = linear.in_features
        self.out_features = linear.out_features
        qw, scales = weight_quantize(linear.weight)
        self.register_buffer("weight", qw)
        self.register_buffer("weight_scale", scales)
        if linear.bias is not None:
            self.register_buffer("bias", Tensor._wrap(linear.bias._data))
        else:
            self.bias = None

    def forward(self, x):
        return weight_only_linear(x, self.weight, self.bias,
                                  self.weight_scale)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, int8")


def quantize_for_decode(model, include=None, min_features=0):
    """Swap eligible nn.Linear sublayers for WeightOnlyLinear, in place.

    ``include``: optional predicate ``(qualified_name, layer) -> bool``;
    default quantizes every Linear whose in_features >= min_features (use
    min_features to keep small projections and heads in bf16). Returns the
    model and the number of layers swapped."""
    from . import Linear

    swapped = 0
    for name, sub in list(model.named_sublayers(include_self=True)):
        # children live in _sub_layers (attribute assignment routes Layer
        # values there too; LayerList/Sequential children are ONLY there)
        for child_name, child in list(sub._sub_layers.items()):
            if not isinstance(child, Linear):
                continue
            qual = f"{name}.{child_name}" if name else child_name
            if child.in_features < min_features:
                continue
            if include is not None and not include(qual, child):
                continue
            setattr(sub, child_name, WeightOnlyLinear(child))
            swapped += 1
    return model, swapped
