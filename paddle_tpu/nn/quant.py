"""Weight-only quantization for the decode path.

Reference capability: ``paddle.nn.quant.weight_quantize`` /
``weight_only_linear`` backing ``fused_multi_transformer_int8_op.cu``
(SURVEY A3.x) — small-batch decode is weight-bandwidth-bound, so int8
weights halve the dominant HBM traffic. TPU design: weights are STORED
int8 with one f32 scale per output channel (symmetric).

Two GEMM backends, selected by ``FLAGS_weight_only_quant_backend``:

* ``pallas`` (default on TPU) — ``ops/pallas/quant_matmul.py``: dequant
  happens inside the kernel in VMEM; packed int4 unpacks its nibbles
  in-kernel, ONE pass over the weight bytes, one fused kernel per GEMM.
* ``xla`` (default elsewhere) — ``x @ convert(W_int8)`` riding XLA
  convert-fusion; int4 runs as two dots over nibble halves so the shifts
  stay fusible unary chains.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.flags import get_flags
from ..framework.tensor import Tensor, apply_op
from .layer import Layer

__all__ = ["weight_quantize", "weight_only_linear", "WeightOnlyLinear",
           "quantize_for_decode", "quant_backend"]


def quant_backend(rows=None) -> str:
    """Resolve the active weight-only GEMM backend ('pallas' | 'xla').

    ``auto`` picks the fused Pallas kernel on TPU and the XLA
    convert-fusion path elsewhere. ``rows`` (when known) routes
    prefill-wide batches back to XLA even under ``auto``+TPU: at
    compute-bound row counts the MXU-saturating XLA dot wins and the
    fused kernel's bandwidth advantage is moot."""
    val = get_flags("FLAGS_weight_only_quant_backend")[
        "FLAGS_weight_only_quant_backend"]
    if val not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"FLAGS_weight_only_quant_backend: {val!r} not in "
            "('auto', 'pallas', 'xla')")
    if val == "auto":
        from ..ops.pallas.quant_matmul import PALLAS_MAX_ROWS

        if jax.default_backend() != "tpu":
            return "xla"
        if rows is not None and rows > PALLAS_MAX_ROWS:
            return "xla"
        return "pallas"
    return val


def _t(x):
    return x if isinstance(x, Tensor) else Tensor._wrap(jnp.asarray(x))


def weight_quantize(x, algo="weight_only_int8"):
    """Per-output-channel symmetric quantization of a [in, out] weight.

    * ``weight_only_int8`` → ``(int8 weight [in, out], f32 scales [out])``
    * ``weight_only_int4`` → ``(int8 weight [in/2, out], f32 scales
      [out])`` — two nibbles packed per byte (rows 2k at the low nibble,
      2k+1 at the high nibble), range [-7, 7], so the weight stream is a
      QUARTER of bf16 (VERDICT r3 #9; reference:
      paddle.nn.quant.weight_quantize int4 path).
    """
    w = _t(x)._data
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    if algo == "weight_only_int8":
        scales = jnp.maximum(amax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / scales[None, :]),
                     -127, 127).astype(jnp.int8)
        return Tensor._wrap(q), Tensor._wrap(scales)
    if algo == "weight_only_int4":
        if w.shape[0] % 2:
            raise ValueError("weight_only_int4 needs even in_features "
                             f"(got {w.shape[0]})")
        scales = jnp.maximum(amax, 1e-8) / 7.0
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / scales[None, :]),
                     -7, 7).astype(jnp.int8)
        packed = jnp.bitwise_or(
            jnp.bitwise_and(q[0::2], jnp.int8(0x0F)),
            jnp.left_shift(q[1::2], 4))
        return Tensor._wrap(packed), Tensor._wrap(scales)
    raise NotImplementedError(
        f"weight_quantize: unsupported algo {algo!r}")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8"):
    """y = x @ dequant(W) + b with int8- or int4-stored W (reference:
    paddle.nn.quant.weight_only_linear). Backend per ``quant_backend()``.

    XLA path: int4 runs as TWO dots — even input columns against the
    sign-extended low nibbles, odd columns against the high nibbles — so
    the nibble shifts stay elementwise unary chains XLA fuses into the
    dot operand loads (an unpack-to-[in,out] would materialize a
    full-width weight and forfeit the bandwidth win). Pallas path: one
    fused dequant-in-kernel matmul (``ops/pallas/quant_matmul.py``)."""
    if weight_dtype not in ("int8", "int4"):
        raise NotImplementedError("weight_only_linear: int8/int4 only")
    args = [_t(x), _t(weight), _t(weight_scale)]
    has_bias = bias is not None
    if has_bias:
        args.append(_t(bias))
    # resolved at trace time from static shape + flag: recorded programs
    # bake the backend in, exactly like the reference's gflags dispatch
    rows = 1
    for d in _t(x)._data.shape[:-1]:
        rows *= int(d)
    backend = quant_backend(rows=rows)

    def fn(xa, wq, sc, *b):
        bias_a = b[0] if b else None
        if backend == "pallas":
            from ..ops.pallas.quant_matmul import quant_matmul

            return quant_matmul(xa, wq, sc, bias=bias_a,
                                weight_dtype=weight_dtype)
        return quant_matmul_xla(xa, wq, sc, bias=bias_a,
                                weight_dtype=weight_dtype)

    return apply_op(fn, *args)


def quant_matmul_xla(xa, wq, sc, bias=None, weight_dtype="int8"):
    """Raw-array XLA backend: int8 rides convert-fusion into the dot's
    operand load; int4 runs as two dots over the nibble halves so the
    shifts stay fusible unary chains (an unpack-to-[in,out] would
    materialize a full-width weight and forfeit the bandwidth win)."""
    if weight_dtype == "int4":
        lo = jnp.right_shift(jnp.left_shift(wq, 4), 4).astype(xa.dtype)
        hi = jnp.right_shift(wq, 4).astype(xa.dtype)
        y = (jnp.dot(xa[..., 0::2], lo,
                     preferred_element_type=jnp.float32)
             + jnp.dot(xa[..., 1::2], hi,
                       preferred_element_type=jnp.float32))
    else:
        y = jnp.dot(xa, wq.astype(xa.dtype),
                    preferred_element_type=jnp.float32)
    y = (y * sc.astype(jnp.float32)).astype(xa.dtype)
    if bias is not None:
        y = y + bias.astype(xa.dtype)
    return y


class WeightOnlyLinear(Layer):
    """Drop-in decode-path replacement for nn.Linear with an int8 or
    packed-int4 weight.

    Quantized weight and scales are registered as buffers (not
    parameters): a quantized model serves, it does not train.
    """

    def __init__(self, linear, algo="weight_only_int8"):
        super().__init__()
        self.in_features = linear.in_features
        self.out_features = linear.out_features
        self.weight_dtype = "int4" if algo == "weight_only_int4" else "int8"
        qw, scales = weight_quantize(linear.weight, algo=algo)
        self.register_buffer("weight", qw)
        self.register_buffer("weight_scale", scales)
        if linear.bias is not None:
            self.register_buffer("bias", Tensor._wrap(linear.bias._data))
        else:
            self.bias = None

    def forward(self, x):
        return weight_only_linear(x, self.weight, self.bias,
                                  self.weight_scale,
                                  weight_dtype=self.weight_dtype)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, {self.weight_dtype}")


def quantize_for_decode(model, include=None, min_features=0,
                        algo="weight_only_int8"):
    """Swap eligible nn.Linear sublayers for WeightOnlyLinear, in place.

    ``include``: optional predicate ``(qualified_name, layer) -> bool``;
    default quantizes every Linear whose in_features >= min_features (use
    min_features to keep small projections and heads in bf16). ``algo``:
    ``weight_only_int8`` or ``weight_only_int4`` (int4 skips odd
    in_features layers, which cannot nibble-pack). Returns the model and
    the number of layers swapped."""
    from . import Linear

    swapped = 0
    for name, sub in list(model.named_sublayers(include_self=True)):
        # children live in _sub_layers (attribute assignment routes Layer
        # values there too; LayerList/Sequential children are ONLY there)
        for child_name, child in list(sub._sub_layers.items()):
            if not isinstance(child, Linear):
                continue
            qual = f"{name}.{child_name}" if name else child_name
            if child.in_features < min_features:
                continue
            if algo == "weight_only_int4" and child.in_features % 2:
                continue
            if include is not None and not include(qual, child):
                continue
            setattr(sub, child_name, WeightOnlyLinear(child, algo=algo))
            swapped += 1
    return model, swapped
