"""Weight initializers (reference: python/paddle/nn/initializer/).

Initializers are pure: ``init(shape, dtype, key) -> jax array``. Layers call
them with deterministic keys derived from the global seed + parameter name,
which is what makes multi-process init reproducible without the reference's
init-broadcast step (SURVEY.md C19).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "calculate_gain",
]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *spatial] (paddle OIHW layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a**2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


class Initializer:
    def __call__(self, shape, dtype, key):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype, key):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype, key):
        return self.mean + self.std * jax.random.normal(key, shape, jnp.float32).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype, key):
        x = jax.random.truncated_normal(key, self.a, self.b, shape, jnp.float32)
        return (self.mean + self.std * x).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype, key):
        return jax.random.uniform(key, shape, jnp.float32, self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, jnp.float32).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype, key):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, jnp.float32, -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype, key):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(key, shape, jnp.float32).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype, key):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, jnp.float32, -limit, limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype, key):
        arr = jnp.asarray(getattr(self.value, "_data", self.value), dtype=dtype)
        assert tuple(arr.shape) == tuple(shape), f"Assign shape {arr.shape} != {shape}"
        return arr
