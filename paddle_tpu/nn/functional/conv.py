"""Convolutions via lax.conv_general_dilated (MXU path).

Reference: paddle/phi/kernels/gpu/conv_kernel.cu (cuDNN). XLA lowers these
directly onto the MXU with layout assignment; no per-backend kernel needed.
Weight layout is paddle's OIHW; activations NCHW by default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...amp import amp_cast
from ...framework.tensor import Tensor, apply_op

__all__ = ["conv1d", "conv2d", "conv3d", "conv2d_transpose"]


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _padding_arg(padding, nd):
    if isinstance(padding, str):
        return padding.upper()
    p = _pair(padding, nd)
    if len(p) == nd:
        return [(int(x), int(x)) for x in p]
    # already pairs
    return [tuple(pp) for pp in p]


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    x, weight = amp_cast("conv2d", _t(x), _t(weight))
    s, d = _pair(stride), _pair(dilation)
    pad = _padding_arg(padding, 2)
    dn = (data_format, "OIHW", data_format)

    def fn(a, w):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=s, padding=pad, rhs_dilation=d,
            feature_group_count=groups, dimension_numbers=dn,
        )
        return out

    out = apply_op(fn, x, weight)
    if bias is not None:
        bias = _t(bias)
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = apply_op(lambda o, b: o + b.reshape(shape), out, bias)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    x, weight = amp_cast("conv1d", _t(x), _t(weight))
    s, d = _pair(stride, 1), _pair(dilation, 1)
    pad = _padding_arg(padding, 1)
    dn = ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "OIH", "NHC")

    def fn(a, w):
        return jax.lax.conv_general_dilated(
            a, w, window_strides=s, padding=pad, rhs_dilation=d,
            feature_group_count=groups, dimension_numbers=dn,
        )

    out = apply_op(fn, x, weight)
    if bias is not None:
        out = apply_op(lambda o, b: o + b.reshape(1, -1, 1), out, _t(bias))
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    x, weight = amp_cast("conv3d", _t(x), _t(weight))
    s, d = _pair(stride, 3), _pair(dilation, 3)
    pad = _padding_arg(padding, 3)
    dn = (data_format, "OIDHW", data_format)

    def fn(a, w):
        return jax.lax.conv_general_dilated(
            a, w, window_strides=s, padding=pad, rhs_dilation=d,
            feature_group_count=groups, dimension_numbers=dn,
        )

    out = apply_op(fn, x, weight)
    if bias is not None:
        out = apply_op(lambda o, b: o + b.reshape(1, -1, 1, 1, 1), out, _t(bias))
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCHW"):
    """Transposed conv as an lhs-dilated regular conv (the grouped form
    jax.lax.conv_transpose lacks). Paddle weight layout: [in_c, out_c/groups,
    kh, kw]; out_hw = (in-1)*s - 2*p + d*(k-1) + output_padding + 1."""
    x, weight = amp_cast("conv2d", _t(x), _t(weight))
    s, d = _pair(stride), _pair(dilation)
    p = _pair(padding)
    kh, kw = weight._data.shape[-2:]
    if data_format == "NHWC":
        in_hw = x._data.shape[1:3]
    else:
        in_hw = x._data.shape[2:4]
    if output_size is not None:
        osz = _pair(output_size)
        op = tuple(
            osz[i] - ((in_hw[i] - 1) * s[i] - 2 * p[i] + d[i] * ((kh, kw)[i] - 1) + 1)
            for i in range(2)
        )
    else:
        op = _pair(output_padding)
    if any(o < 0 or o >= s[i] for i, o in enumerate(op)):
        raise ValueError(
            f"conv2d_transpose: invalid output_padding {op} for stride {s}"
        )

    def fn(a, w):
        i_c, ocg = w.shape[0], w.shape[1]
        # [I, O/g, kh, kw] -> [O, I/g, kh, kw], spatially flipped (transposed
        # conv correlates with the flipped kernel)
        wg = w.reshape(groups, i_c // groups, ocg, kh, kw)
        wg = jnp.flip(jnp.transpose(wg, (0, 2, 1, 3, 4)), axis=(-2, -1))
        wk = wg.reshape(groups * ocg, i_c // groups, kh, kw)
        pad = [
            (d[0] * (kh - 1) - p[0], d[0] * (kh - 1) - p[0] + op[0]),
            (d[1] * (kw - 1) - p[1], d[1] * (kw - 1) - p[1] + op[1]),
        ]
        return jax.lax.conv_general_dilated(
            a, wk, window_strides=(1, 1), padding=pad,
            lhs_dilation=s, rhs_dilation=d,
            dimension_numbers=(data_format, "OIHW", data_format),
            feature_group_count=groups,
        )

    out = apply_op(fn, x, weight)
    if bias is not None:
        bshape = (1, 1, 1, -1) if data_format == "NHWC" else (1, -1, 1, 1)
        out = apply_op(lambda o, b: o + b.reshape(bshape), out, _t(bias))
    return out
