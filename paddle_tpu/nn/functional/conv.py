"""Convolutions via lax.conv_general_dilated (MXU path).

Reference: paddle/phi/kernels/gpu/conv_kernel.cu (cuDNN). XLA lowers these
directly onto the MXU with layout assignment; no per-backend kernel needed.
Weight layout is paddle's OIHW; activations NCHW by default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...amp import amp_cast
from ...framework.tensor import Tensor, apply_op

__all__ = ["conv1d", "conv2d", "conv3d", "conv2d_transpose"]


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _padding_arg(padding, nd):
    if isinstance(padding, str):
        return padding.upper()
    p = _pair(padding, nd)
    if len(p) == nd:
        return [(int(x), int(x)) for x in p]
    # already pairs
    return [tuple(pp) for pp in p]


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    x, weight = amp_cast("conv2d", _t(x), _t(weight))
    s, d = _pair(stride), _pair(dilation)
    pad = _padding_arg(padding, 2)
    dn = (data_format, "OIHW", data_format)

    def fn(a, w):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=s, padding=pad, rhs_dilation=d,
            feature_group_count=groups, dimension_numbers=dn,
        )
        return out

    out = apply_op(fn, x, weight)
    if bias is not None:
        bias = _t(bias)
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = apply_op(lambda o, b: o + b.reshape(shape), out, bias)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    x, weight = amp_cast("conv1d", _t(x), _t(weight))
    s, d = _pair(stride, 1), _pair(dilation, 1)
    pad = _padding_arg(padding, 1)
    dn = ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "OIH", "NHC")

    def fn(a, w):
        return jax.lax.conv_general_dilated(
            a, w, window_strides=s, padding=pad, rhs_dilation=d,
            feature_group_count=groups, dimension_numbers=dn,
        )

    out = apply_op(fn, x, weight)
    if bias is not None:
        out = apply_op(lambda o, b: o + b.reshape(1, -1, 1), out, _t(bias))
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    x, weight = amp_cast("conv3d", _t(x), _t(weight))
    s, d = _pair(stride, 3), _pair(dilation, 3)
    pad = _padding_arg(padding, 3)
    dn = (data_format, "OIDHW", data_format)

    def fn(a, w):
        return jax.lax.conv_general_dilated(
            a, w, window_strides=s, padding=pad, rhs_dilation=d,
            feature_group_count=groups, dimension_numbers=dn,
        )

    out = apply_op(fn, x, weight)
    if bias is not None:
        out = apply_op(lambda o, b: o + b.reshape(1, -1, 1, 1, 1), out, _t(bias))
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCHW"):
    x, weight = amp_cast("conv2d", _t(x), _t(weight))
    s, d = _pair(stride), _pair(dilation)
    p = _pair(padding)

    def fn(a, w):
        # paddle transpose-conv weight layout: [in_c, out_c/groups, kh, kw]
        return jax.lax.conv_transpose(
            a, w, strides=s,
            padding=[(p[0], p[0]), (p[1], p[1])],
            rhs_dilation=d,
            dimension_numbers=(data_format, "IOHW", data_format),
            transpose_kernel=True,
        )

    out = apply_op(fn, x, weight)
    if bias is not None:
        out = apply_op(lambda o, b: o + b.reshape(1, -1, 1, 1), out, _t(bias))
    return out
