"""Pooling via lax.reduce_window (reference: paddle/phi/kernels/gpu/pool_kernel.cu)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor, apply_op

__all__ = ["max_pool2d", "avg_pool2d", "max_pool1d", "avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool1d", "adaptive_max_pool2d"]


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    k, s = _pair(kernel_size), _pair(stride if stride is not None else kernel_size)
    p = _pair(padding)

    def fn(a):
        neg = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
        return jax.lax.reduce_window(
            a, neg, jax.lax.max,
            window_dimensions=(1, 1, k[0], k[1]),
            window_strides=(1, 1, s[0], s[1]),
            padding=((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])),
        )

    return apply_op(fn, _t(x))


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    k, s = _pair(kernel_size), _pair(stride if stride is not None else kernel_size)
    p = _pair(padding)

    def fn(a):
        summed = jax.lax.reduce_window(
            a, 0.0, jax.lax.add,
            window_dimensions=(1, 1, k[0], k[1]),
            window_strides=(1, 1, s[0], s[1]),
            padding=((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])),
        )
        if divisor_override:
            return summed / divisor_override
        if exclusive and (p[0] or p[1]):
            ones = jnp.ones(a.shape[-2:], a.dtype)[None, None]
            counts = jax.lax.reduce_window(
                jnp.broadcast_to(ones, (1, 1) + a.shape[-2:]), 0.0, jax.lax.add,
                window_dimensions=(1, 1, k[0], k[1]),
                window_strides=(1, 1, s[0], s[1]),
                padding=((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])),
            )
            return summed / counts
        return summed / (k[0] * k[1])

    return apply_op(fn, _t(x))


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False):
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = k if stride is None else (stride if isinstance(stride, int) else stride[0])
    p = padding if isinstance(padding, int) else padding[0]

    def fn(a):
        return jax.lax.reduce_window(
            a, -jnp.inf, jax.lax.max,
            window_dimensions=(1, 1, k), window_strides=(1, 1, s),
            padding=((0, 0), (0, 0), (p, p)),
        )

    return apply_op(fn, _t(x))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False):
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = k if stride is None else (stride if isinstance(stride, int) else stride[0])
    p = padding if isinstance(padding, int) else padding[0]

    def fn(a):
        summed = jax.lax.reduce_window(
            a, 0.0, jax.lax.add,
            window_dimensions=(1, 1, k), window_strides=(1, 1, s),
            padding=((0, 0), (0, 0), (p, p)),
        )
        return summed / k

    return apply_op(fn, _t(x))


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    x = _t(x)
    oh, ow = _pair(output_size)
    _, _, h, w = x._data.shape
    if h % oh == 0 and w % ow == 0:
        kh, kw = h // oh, w // ow

        def fn(a):
            return jax.lax.reduce_window(
                a, 0.0, jax.lax.add,
                window_dimensions=(1, 1, kh, kw), window_strides=(1, 1, kh, kw),
                padding="VALID",
            ) / (kh * kw)

        return apply_op(fn, x)

    # general: mean over index buckets
    def fn(a):
        hs = np.linspace(0, h, oh + 1).astype(int)
        ws = np.linspace(0, w, ow + 1).astype(int)
        rows = [jnp.stack([a[..., hs[i]:hs[i + 1], ws[j]:ws[j + 1]].mean(axis=(-1, -2))
                           for j in range(ow)], axis=-1) for i in range(oh)]
        return jnp.stack(rows, axis=-2)

    return apply_op(fn, x)


def adaptive_avg_pool1d(x, output_size):
    x = _t(x)
    o = output_size if isinstance(output_size, int) else output_size[0]
    l = x._data.shape[-1]
    assert l % o == 0, "adaptive_avg_pool1d requires divisible length"
    k = l // o

    def fn(a):
        return a.reshape(*a.shape[:-1], o, k).mean(-1)

    return apply_op(fn, x)


def adaptive_max_pool2d(x, output_size, return_mask=False):
    x = _t(x)
    oh, ow = _pair(output_size)
    _, _, h, w = x._data.shape
    assert h % oh == 0 and w % ow == 0
    kh, kw = h // oh, w // ow

    def fn(a):
        return jax.lax.reduce_window(
            a, -jnp.inf, jax.lax.max,
            window_dimensions=(1, 1, kh, kw), window_strides=(1, 1, kh, kw),
            padding="VALID",
        )

    return apply_op(fn, x)
