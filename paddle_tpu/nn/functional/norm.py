"""Normalization functional ops (reference: paddle/phi/kernels/gpu/
{batch_norm,layer_norm,group_norm}_kernel.cu; rms_norm fusion kernel).

Stats math runs in fp32 regardless of input dtype (TPU bf16 discipline);
outputs cast back to the input dtype. XLA fuses the whole normalization into
neighbouring ops, replacing the reference's hand-fused variants.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor, apply_op

__all__ = ["batch_norm", "layer_norm", "group_norm", "rms_norm", "local_response_norm"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None):
    """Returns output; updates running stats in-place on the passed tensors
    when training (matching paddle's mutable-buffer semantics)."""
    x = _t(x)
    nd = x._data.ndim
    channel_last = data_format in ("NHWC", "NLC", "NDHWC", "NHC")
    ch_axis = (nd - 1) if (channel_last and nd > 2) else 1
    axes = tuple(i for i in range(nd) if i != ch_axis)
    shape = [1] * nd
    shape[ch_axis] = -1
    use_stats = (not training) if use_global_stats is None else use_global_stats

    if use_stats:
        mean = running_mean._data.astype(jnp.float32)
        var = running_var._data.astype(jnp.float32)

        def fn(a, *wb):
            xf = a.astype(jnp.float32)
            out = (xf - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
            out = _affine(out, wb, shape)
            return out.astype(a.dtype)

        args = [p for p in (weight, bias) if p is not None]
        return apply_op(fn, x, *args)

    # training: batch stats + update running buffers eagerly (host-side state)
    def fn(a, *wb):
        xf = a.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        out = (xf - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        out = _affine(out, wb, shape)
        return out.astype(a.dtype), mean, var

    args = [p for p in (weight, bias) if p is not None]
    out, mean_t, var_t = apply_op(fn, x, *args)
    # buffer update: not differentiated
    rm, rv = running_mean._data.astype(jnp.float32), running_var._data.astype(jnp.float32)
    running_mean._data = (momentum * rm + (1 - momentum) * mean_t._data).astype(running_mean.dtype)
    running_var._data = (momentum * rv + (1 - momentum) * var_t._data).astype(running_var.dtype)
    return out


def _affine(out, wb, shape):
    if len(wb) == 2:
        w, b = wb
        return out * w.astype(out.dtype).reshape(shape) + b.astype(out.dtype).reshape(shape)
    if len(wb) == 1:
        return out * wb[0].astype(out.dtype).reshape(shape)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    x = _t(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    nd = len(tuple(normalized_shape))
    axes = tuple(range(x._data.ndim - nd, x._data.ndim))

    def fn(a, *wb):
        xf = a.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
        if len(wb) == 2:
            out = out * wb[0].astype(jnp.float32) + wb[1].astype(jnp.float32)
        elif len(wb) == 1:
            out = out * wb[0].astype(jnp.float32)
        return out.astype(a.dtype)

    args = [_t(p) for p in (weight, bias) if p is not None]
    return apply_op(fn, x, *args)


def rms_norm(x, weight=None, epsilon=1e-6, axis=-1):
    """RMSNorm (reference: paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu —
    here a plain jnp composite; XLA fuses it)."""
    x = _t(x)

    def fn(a, *w):
        xf = a.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=axis, keepdims=True)
        out = xf * jax.lax.rsqrt(ms + epsilon)
        if w:
            out = out * w[0].astype(jnp.float32)
        return out.astype(a.dtype)

    args = [_t(weight)] if weight is not None else []
    return apply_op(fn, x, *args)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format="NCHW"):
    x = _t(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC", "NHC")

    def fn(a, *wb):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
        n, c = a.shape[:2]
        spatial = a.shape[2:]
        xf = a.astype(jnp.float32).reshape(n, num_groups, c // num_groups, *spatial)
        axes = tuple(range(2, xf.ndim))
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
        shape = [1, c] + [1] * len(spatial)
        if len(wb) == 2:
            out = out * wb[0].astype(jnp.float32).reshape(shape) + wb[1].astype(jnp.float32).reshape(shape)
        out = out.astype(a.dtype)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [_t(p) for p in (weight, bias) if p is not None]
    return apply_op(fn, x, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0):
    x = _t(x)

    def fn(a):
        sq = jnp.square(a)
        half = size // 2
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            window_dimensions=(1, size, 1, 1), window_strides=(1, 1, 1, 1),
            padding=((0, 0), (half, size - 1 - half), (0, 0), (0, 0)),
        )
        return a / jnp.power(k + alpha * summed, beta)

    return apply_op(fn, x)
