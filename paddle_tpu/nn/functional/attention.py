"""Attention functional ops.

``flash_attention`` mirrors the reference's API
(python/paddle/nn/functional/flash_attention.py over
paddle/phi/kernels/gpu/flash_attn_kernel.cu) and routes to the Pallas flash
kernel (paddle_tpu/ops/pallas/flash_attention.py) when shapes are MXU-tile
aligned on TPU, else to an XLA-fused naive composite (still O(S^2) memory —
the kernel is the memory win).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...amp import amp_cast
from ...framework.flags import get_flags
from ...framework.tensor import Tensor, apply_op

__all__ = ["scaled_dot_product_attention", "flash_attention", "naive_attention"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def naive_attention(q, k, v, causal=False, scale=None, bias=None):
    """Pure-jax reference attention on [B, S, H, D] arrays (paddle layout)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d**0.5)
    # [B,S,H,D] -> [B,H,S,D]
    qt, kt, vt = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) * s
    if bias is not None:
        logits = logits + bias
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(qt.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, training=True, name=None):
    """Inputs [batch, seq, num_heads, head_dim] (paddle flash_attention layout).

    Returns (out, softmax_lse_placeholder) like the reference API; the second
    element is None unless return_softmax (discouraged — defeats the fusion).
    """
    q, k, v = amp_cast("attention", _t(query), _t(key), _t(value))
    use_pallas = bool(get_flags("FLAGS_use_flash_attention")["FLAGS_use_flash_attention"])

    def fn(qa, ka, va):
        if use_pallas and _pallas_ok(qa, ka):
            from ...ops.pallas.flash_attention import flash_attention_fused

            return flash_attention_fused(qa, ka, va, causal=causal)
        return naive_attention(qa, ka, va, causal=causal)

    out = apply_op(fn, q, k, v)
    if dropout > 0.0 and training:
        from .common import dropout as _dropout

        out = _dropout(out, p=dropout, training=True)
    if return_softmax:
        probs = apply_op(lambda qa, ka: _softmax_probs(qa, ka, causal), q, k)
        return out, probs
    return out, None


def _softmax_probs(qa, ka, causal):
    d = qa.shape[-1]
    qt, kt = jnp.swapaxes(qa, 1, 2), jnp.swapaxes(ka, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) / (d**0.5)
    if causal:
        s = logits.shape[-1]
        logits = jnp.where(jnp.tril(jnp.ones((s, s), bool)), logits, -jnp.inf)
    return jax.nn.softmax(logits, -1)


def _pallas_ok(qa, ka) -> bool:
    if jax.default_backend() != "tpu":
        return False
    _, sq, _, d = qa.shape
    sk = ka.shape[1]
    return sq % 128 == 0 and sk % 128 == 0 and d in (64, 128, 256) and qa.shape[2] == ka.shape[2]


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True):
    """paddle.nn.functional.scaled_dot_product_attention parity
    ([B, S, H, D] layout, mask broadcastable to [B, H, Sq, Sk])."""
    if attn_mask is None:
        out, _ = flash_attention(query, key, value, dropout=dropout_p, causal=is_causal,
                                 training=training)
        return out
    q, k, v = amp_cast("attention", _t(query), _t(key), _t(value))
    mask = attn_mask._data if isinstance(attn_mask, Tensor) else jnp.asarray(attn_mask)

    def fn(qa, ka, va):
        bias = mask if mask.dtype != jnp.bool_ else jnp.where(mask, 0.0, -jnp.inf)
        return naive_attention(qa, ka, va, causal=is_causal, bias=bias)

    out = apply_op(fn, q, k, v)
    if dropout_p > 0.0 and training:
        from .common import dropout as _dropout

        out = _dropout(out, p=dropout_p, training=True)
    return out
