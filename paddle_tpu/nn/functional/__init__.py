"""nn.functional (reference: python/paddle/nn/functional/).

Every function takes Tensors, applies the active AMP policy, and routes the
pure-jax computation through apply_op so both eager autograd and jit tracing
work. Convs/matmuls hit the MXU via lax; normalization/softmax stay fp32
under AMP.
"""
from .common import (
    linear,
    dropout,
    embedding,
    pad,
    interpolate,
    unfold,
    one_hot,
    label_smooth,
    cosine_similarity,
    normalize,
)
from .conv import conv1d, conv2d, conv3d, conv2d_transpose
from .pooling import (
    avg_pool1d,
    avg_pool2d,
    max_pool1d,
    max_pool2d,
    adaptive_avg_pool1d,
    adaptive_avg_pool2d,
    adaptive_max_pool2d,
)
from .norm import batch_norm, layer_norm, group_norm, rms_norm, local_response_norm
from .activation import (
    relu,
    relu6,
    relu_,
    gelu,
    silu,
    swish,
    sigmoid,
    tanh,
    softmax,
    log_softmax,
    leaky_relu,
    elu,
    selu,
    celu,
    hardswish,
    hardsigmoid,
    hardtanh,
    hardshrink,
    softshrink,
    softplus,
    softsign,
    mish,
    tanhshrink,
    prelu,
    glu,
    gumbel_softmax,
)
from .loss import (
    cross_entropy,
    softmax_with_cross_entropy,
    binary_cross_entropy,
    binary_cross_entropy_with_logits,
    mse_loss,
    l1_loss,
    nll_loss,
    kl_div,
    smooth_l1_loss,
    margin_ranking_loss,
    cosine_embedding_loss,
    ctc_loss,
    square_error_cost,
)
from .attention import scaled_dot_product_attention, flash_attention
from .extras import *  # noqa: F401,F403

__all__ = [n for n in dir() if not n.startswith("_")]
