"""Activation functional ops (reference: paddle/phi/kernels/gpu/activation_kernel.cu)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import random as _random
from ...framework.tensor import Tensor, apply_op

__all__ = ["relu", "relu_", "relu6", "gelu", "silu", "swish", "sigmoid", "tanh",
           "softmax", "log_softmax", "leaky_relu", "elu", "selu", "celu",
           "hardswish", "hardsigmoid", "hardtanh", "hardshrink", "softshrink",
           "softplus", "softsign", "mish", "tanhshrink", "prelu", "glu",
           "gumbel_softmax"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _u(fn, x, **kw):
    return apply_op(lambda a: fn(a, **kw), _t(x))


def relu(x, name=None):
    return _u(jax.nn.relu, x)


def relu_(x):
    out = relu(x)
    x.set_value(out)
    return x


def relu6(x):
    return _u(jax.nn.relu6, x)


def gelu(x, approximate=False):
    return _u(lambda a: jax.nn.gelu(a, approximate=approximate), x)


def silu(x):
    return _u(jax.nn.silu, x)


def swish(x):
    return _u(jax.nn.silu, x)


def sigmoid(x):
    return _u(jax.nn.sigmoid, x)


def tanh(x):
    return _u(jnp.tanh, x)


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        af = a.astype(jnp.float32)
        out = jax.nn.softmax(af, axis=axis)
        return out.astype(a.dtype if dtype is None else dtype)

    return apply_op(fn, _t(x))


def log_softmax(x, axis=-1, dtype=None):
    def fn(a):
        af = a.astype(jnp.float32)
        out = jax.nn.log_softmax(af, axis=axis)
        return out.astype(a.dtype if dtype is None else dtype)

    return apply_op(fn, _t(x))


def leaky_relu(x, negative_slope=0.01):
    return _u(lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0):
    return _u(lambda a: jax.nn.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return _u(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0):
    return _u(lambda a: jax.nn.celu(a, alpha), x)


def hardswish(x):
    return _u(jax.nn.hard_swish, x)


def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return _u(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x)


def hardtanh(x, min=-1.0, max=1.0):
    return _u(lambda a: jnp.clip(a, min, max), x)


def hardshrink(x, threshold=0.5):
    return _u(lambda a: jnp.where(jnp.abs(a) > threshold, a, jnp.zeros((), a.dtype)), x)


def softshrink(x, threshold=0.5):
    return _u(lambda a: jnp.sign(a) * jnp.maximum(jnp.abs(a) - threshold, 0.0), x)


def softplus(x, beta=1.0, threshold=20.0):
    return _u(lambda a: jnp.where(beta * a > threshold, a, jnp.log1p(jnp.exp(beta * a)) / beta), x)


def softsign(x):
    return _u(jax.nn.soft_sign, x)


def mish(x):
    return _u(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x)


def tanhshrink(x):
    return _u(lambda a: a - jnp.tanh(a), x)


def prelu(x, weight):
    return apply_op(lambda a, w: jnp.where(a > 0, a, w.reshape((1, -1) + (1,) * (a.ndim - 2)) * a), _t(x), _t(weight))


def glu(x, axis=-1):
    def fn(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)

    return apply_op(fn, _t(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    key = _random.op_key()

    def fn(a):
        g = -jnp.log(-jnp.log(jax.random.uniform(key, a.shape) + 1e-20) + 1e-20)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y).at[
                tuple(jnp.indices(y.shape)[i] if i != (axis % y.ndim) else idx
                      for i in range(y.ndim))
            ].set(1.0)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return apply_op(fn, _t(x))
