"""Common functional ops: linear, dropout, embedding, pad, interpolate."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...amp import amp_cast
from ...framework import random as _random
from ...framework.tensor import Tensor, apply_op

__all__ = ["linear", "dropout", "embedding", "pad", "interpolate", "unfold",
           "one_hot", "label_smooth", "cosine_similarity", "normalize"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle weight layout [in_features, out_features]
    (reference: paddle/phi/kernels/impl/matmul_kernel_impl.h via nn.Linear)."""
    x, weight = amp_cast("linear", _t(x), _t(weight))
    if bias is not None:
        (bias,) = amp_cast("linear", _t(bias))
        return apply_op(lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias)
    return apply_op(jnp.matmul, x, weight)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = _t(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    key = _random.op_key()

    def fn(a):
        shape = a.shape if axis is None else tuple(
            a.shape[i] if (i in (axis if isinstance(axis, (list, tuple)) else [axis])) else 1
            for i in range(a.ndim)
        )
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        out = jnp.where(keep, a, jnp.zeros((), a.dtype))
        if mode == "upscale_in_train":
            out = out / (1.0 - p)
        return out

    return apply_op(fn, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Lookup rows of weight [vocab, dim] (reference: phi embedding kernel;
    vocab-parallel variant lives in distributed.fleet.meta_parallel)."""
    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    weight = _t(weight)

    def fn(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out

    return apply_op(fn, weight)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    x = _t(x)

    def fn(a):
        if isinstance(pad, (list, tuple)) and len(pad) == a.ndim * 2:
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(a.ndim)]
        else:
            # paddle style: pad applies to last len(pad)//2 dims, reversed pairs
            n = len(pad) // 2
            widths = [(0, 0)] * (a.ndim - n)
            for i in range(n):
                widths.append((pad[2 * i], pad[2 * i + 1]))
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, widths, mode=jmode, constant_values=value)
        return jnp.pad(a, widths, mode=jmode)

    return apply_op(fn, x)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW"):
    x = _t(x)
    n, c, h, w = x._data.shape
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else (scale_factor, scale_factor)
        size = (int(h * sf[0]), int(w * sf[1]))
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic"}[mode]

    def fn(a):
        # jax.image.resize operates on spatial dims; NCHW → resize dims 2,3
        return jax.image.resize(a, (a.shape[0], a.shape[1], size[0], size[1]), method=method)

    return apply_op(fn, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    x = _t(x)
    k = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else (kernel_sizes, kernel_sizes)
    s = strides if isinstance(strides, (list, tuple)) else (strides, strides)
    p = paddings if isinstance(paddings, (list, tuple)) else (paddings, paddings)
    d = dilations if isinstance(dilations, (list, tuple)) else (dilations, dilations)

    def fn(a):
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s,
            padding=[(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        n, ckk, oh, ow = patches.shape
        return patches.reshape(n, ckk, oh * ow)

    return apply_op(fn, x)


def one_hot(x, num_classes):
    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor._wrap(jax.nn.one_hot(idx, num_classes))


def label_smooth(label, prior_dist=None, epsilon=0.1):
    label = _t(label)

    def fn(l):
        k = l.shape[-1]
        uniform = 1.0 / k if prior_dist is None else jnp.asarray(getattr(prior_dist, "_data", prior_dist))
        return (1 - epsilon) * l + epsilon * uniform

    return apply_op(fn, label)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return apply_op(
        lambda a, b: jnp.sum(a * b, axis=axis)
        / jnp.maximum(jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis), eps),
        _t(x1), _t(x2),
    )


def normalize(x, p=2, axis=1, epsilon=1e-12):
    return apply_op(
        lambda a: a / jnp.maximum(jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True), epsilon),
        _t(x),
    )
