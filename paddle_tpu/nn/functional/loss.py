"""Loss functional ops (reference: paddle/phi/kernels/gpu/cross_entropy_kernel.cu,
python/paddle/nn/functional/loss.py). All losses compute in fp32."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor, apply_op

__all__ = ["cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
           "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
           "kl_div", "smooth_l1_loss", "margin_ranking_loss",
           "cosine_embedding_loss", "ctc_loss", "square_error_cost"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _reduce(x, reduction):
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    return x


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    """paddle.nn.functional.cross_entropy: input is logits by default."""
    input = _t(input)
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    if lab.ndim == input._data.ndim and not soft_label and lab.shape[axis] == 1:
        lab = jnp.squeeze(lab, axis)

    def fn(logits, *maybe_soft):
        lf = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(lf, axis=axis) if use_softmax else jnp.log(jnp.clip(lf, 1e-15))
        if soft_label:
            soft = maybe_soft[0].astype(jnp.float32)
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            labels = lab
            if label_smoothing > 0.0:
                n = logits.shape[axis]
                onehot = jax.nn.one_hot(labels, n, axis=axis)
                smoothed = onehot * (1 - label_smoothing) + label_smoothing / n
                loss = -jnp.sum(smoothed * logp, axis=axis)
            else:
                loss = -jnp.take_along_axis(logp, jnp.expand_dims(labels, axis), axis=axis).squeeze(axis)
            if weight is not None:
                w = jnp.asarray(getattr(weight, "_data", weight))
                loss = loss * jnp.take(w, labels)
            mask = labels != ignore_index
            loss = jnp.where(mask, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
        return _reduce(loss, reduction)

    if soft_label:
        return apply_op(fn, input, _t(label))
    return apply_op(fn, input)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               axis=-1, return_softmax=False):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax as _softmax

        return loss, _softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    input, label = _t(input), _t(label)

    def fn(p, y):
        pf, yf = p.astype(jnp.float32), y.astype(jnp.float32)
        loss = -(yf * jnp.log(jnp.clip(pf, 1e-12)) + (1 - yf) * jnp.log(jnp.clip(1 - pf, 1e-12)))
        if weight is not None:
            loss = loss * jnp.asarray(getattr(weight, "_data", weight))
        return _reduce(loss, reduction)

    return apply_op(fn, input, label)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None):
    logit, label = _t(logit), _t(label)

    def fn(z, y):
        zf, yf = z.astype(jnp.float32), y.astype(jnp.float32)
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        loss = jnp.maximum(zf, 0) - zf * yf + jnp.log1p(jnp.exp(-jnp.abs(zf)))
        if pos_weight is not None:
            pw = jnp.asarray(getattr(pos_weight, "_data", pos_weight))
            log_w = (pw - 1) * yf + 1
            loss = loss * log_w
        if weight is not None:
            loss = loss * jnp.asarray(getattr(weight, "_data", weight))
        return _reduce(loss, reduction)

    return apply_op(fn, logit, label)


def mse_loss(input, label, reduction="mean"):
    return apply_op(
        lambda a, b: _reduce(jnp.square(a.astype(jnp.float32) - b.astype(jnp.float32)), reduction),
        _t(input), _t(label),
    )


def square_error_cost(input, label):
    return apply_op(lambda a, b: jnp.square(a - b), _t(input), _t(label))


def l1_loss(input, label, reduction="mean"):
    return apply_op(
        lambda a, b: _reduce(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)), reduction),
        _t(input), _t(label),
    )


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    input = _t(input)
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(logp):
        loss = -jnp.take_along_axis(logp, lab[..., None], axis=-1).squeeze(-1)
        if weight is not None:
            loss = loss * jnp.take(jnp.asarray(getattr(weight, "_data", weight)), lab)
        mask = lab != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
        return _reduce(loss, reduction)

    return apply_op(fn, input)


def kl_div(input, label, reduction="mean"):
    return apply_op(
        lambda lp, y: _reduce(y * (jnp.log(jnp.clip(y, 1e-12)) - lp), reduction),
        _t(input), _t(label),
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    def fn(a, b):
        d = a.astype(jnp.float32) - b.astype(jnp.float32)
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce(loss, reduction)

    return apply_op(fn, _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    return apply_op(
        lambda a, b, y: _reduce(jnp.maximum(-y * (a - b) + margin, 0.0), reduction),
        _t(input), _t(other), _t(label),
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y > 0, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(loss, reduction)

    return apply_op(fn, _t(input1), _t(input2), _t(label))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean"):
    raise NotImplementedError(
        "ctc_loss is recorded as a capability gap for this round (SURVEY.md B17 long tail)"
    )
