"""Functional tail (r5, VERDICT r4 coverage: the ~30 paddle.nn.functional
ops earlier rounds skipped — 3-D pooling, 1-D/3-D transposed conv, pixel
ops, the loss tail, instance/local-response norm; reference:
python/paddle/nn/functional/). Same contract as the rest of the package:
Tensors or array-likes in, ``apply_op`` so the tape records VJPs."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as _random
from ...framework.tensor import Tensor, apply_op

__all__ = [
    "max_pool3d", "avg_pool3d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "max_pool2d_with_indices", "max_unpool1d",
    "max_unpool2d",
    "conv1d_transpose", "conv3d_transpose",
    "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
    "log_sigmoid", "rrelu", "maxout", "gumbel_softmax",
    "pairwise_distance", "local_response_norm", "instance_norm",
    "dropout3d", "alpha_dropout", "upsample", "fold",
    "huber_loss", "soft_margin_loss", "multi_label_soft_margin_loss",
    "multi_margin_loss", "hinge_embedding_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "poisson_nll_loss",
    "gaussian_nll_loss",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor._wrap(jnp.asarray(x))


def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * 3


def _reduce(val, reduction):
    if reduction == "none":
        return val
    if reduction == "sum":
        return jnp.sum(val)
    return jnp.mean(val)


# ------------------------------------------------------------- pooling 3d


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    k = _triple(kernel_size)
    s = _triple(stride if stride is not None else kernel_size)
    p = _triple(padding)

    def fn(a):
        neg = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
               else jnp.iinfo(a.dtype).min)
        return jax.lax.reduce_window(
            a, neg, jax.lax.max,
            window_dimensions=(1, 1) + k,
            window_strides=(1, 1) + s,
            padding=((0, 0), (0, 0)) + tuple((pi, pi) for pi in p))

    return apply_op(fn, _t(x))


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None,
               data_format="NCDHW"):
    k = _triple(kernel_size)
    s = _triple(stride if stride is not None else kernel_size)
    p = _triple(padding)

    def fn(a):
        summed = jax.lax.reduce_window(
            a, 0.0, jax.lax.add,
            window_dimensions=(1, 1) + k,
            window_strides=(1, 1) + s,
            padding=((0, 0), (0, 0)) + tuple((pi, pi) for pi in p))
        if divisor_override:
            return summed / divisor_override
        if exclusive and any(p):
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add,
                window_dimensions=(1, 1) + k,
                window_strides=(1, 1) + s,
                padding=((0, 0), (0, 0)) + tuple((pi, pi) for pi in p))
            return summed / counts
        return summed / float(np.prod(k))

    return apply_op(fn, _t(x))


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    out = _triple(output_size)

    def fn(a):
        n, c, d, h, w = a.shape
        if d % out[0] or h % out[1] or w % out[2]:
            raise ValueError(
                f"adaptive_avg_pool3d: input {(d, h, w)} not divisible "
                f"by output {out}")
        a = a.reshape(n, c, out[0], d // out[0], out[1], h // out[1],
                      out[2], w // out[2])
        return a.mean(axis=(3, 5, 7))

    return apply_op(fn, _t(x))


def adaptive_max_pool1d(x, output_size, return_mask=False):
    out = int(output_size)

    def fn(a):
        n, c, l = a.shape
        if l % out:
            raise ValueError(
                f"adaptive_max_pool1d: length {l} not divisible by {out}")
        return a.reshape(n, c, out, l // out).max(axis=-1)

    return apply_op(fn, _t(x))


def max_pool2d_with_indices(x, kernel_size, stride=None, padding=0):
    """Max pool returning (out, flat per-window indices) — the producer
    side of max_unpool2d. Non-overlapping windows only (stride ==
    kernel_size, the unpool contract)."""
    k = kernel_size if isinstance(kernel_size, (tuple, list)) else (
        kernel_size, kernel_size)
    s = stride if stride is not None else k
    s = s if isinstance(s, (tuple, list)) else (s, s)
    if tuple(k) != tuple(s) or padding:
        raise NotImplementedError(
            "max_pool2d_with_indices: non-overlapping windows only "
            "(stride == kernel_size, padding 0)")

    def indices_of(a):
        n, c, h, w = a.shape
        oh, ow = h // k[0], w // k[1]
        win = a[:, :, :oh * k[0], :ow * k[1]].reshape(
            n, c, oh, k[0], ow, k[1]).transpose(0, 1, 2, 4, 3, 5).reshape(
            n, c, oh, ow, k[0] * k[1])
        idx_in_win = jnp.argmax(win, axis=-1)
        # flat index into the ORIGINAL [h, w] map (paddle/torch layout)
        wy = idx_in_win // k[1]
        wx = idx_in_win % k[1]
        oy = jnp.arange(oh)[None, None, :, None] * k[0]
        ox = jnp.arange(ow)[None, None, None, :] * k[1]
        return ((oy + wy) * w + (ox + wx)).astype(jnp.int32)

    xt = _t(x)
    # indices are non-differentiable: compute ONCE untaped, then the
    # taped output is just a gather at those positions (code-review r5:
    # the old form ran the whole windowing twice)
    idx_arr = indices_of(xt._data)

    def gather(a):
        n, c = a.shape[:2]
        return jnp.take_along_axis(
            a.reshape(n, c, -1), idx_arr.reshape(n, c, -1),
            axis=-1).reshape(idx_arr.shape)

    out = apply_op(gather, xt)
    return out, Tensor._wrap(idx_arr)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW"):
    """Scatter pooled values back to their argmax positions (reference:
    paddle.nn.functional.max_unpool2d)."""
    if padding:
        # indices from a padded pool address the padded map; the size
        # formula and scatter layout below would silently be wrong
        raise NotImplementedError(
            "max_unpool2d: padding != 0 not supported (pair with "
            "max_pool2d_with_indices, which enforces padding 0)")
    k = kernel_size if isinstance(kernel_size, (tuple, list)) else (
        kernel_size, kernel_size)
    s = stride if stride is not None else k
    s = s if isinstance(s, (tuple, list)) else (s, s)
    idx = _t(indices)._data.astype(jnp.int32)

    def fn(a):
        n, c, oh, ow = a.shape
        if output_size is not None:
            h, w = output_size[-2], output_size[-1]
        else:
            h, w = (oh - 1) * s[0] + k[0], (ow - 1) * s[1] + k[1]
        flat = jnp.zeros((n, c, h * w), a.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None],
            jnp.arange(c)[None, :, None],
            idx.reshape(n, c, -1)].add(a.reshape(n, c, -1))
        return flat.reshape(n, c, h, w)

    return apply_op(fn, _t(x))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL"):
    if padding:
        raise NotImplementedError(
            "max_unpool1d: padding != 0 not supported (see max_unpool2d)")
    k = kernel_size if not isinstance(kernel_size, (tuple, list)) else (
        kernel_size[0])
    s = stride if stride is not None else k
    s = s[0] if isinstance(s, (tuple, list)) else s
    idx = _t(indices)._data.astype(jnp.int32)

    def fn(a):
        n, c, ol = a.shape
        l = (output_size[-1] if output_size is not None
             else (ol - 1) * s + k)
        flat = jnp.zeros((n, c, l), a.dtype)
        return flat.at[
            jnp.arange(n)[:, None, None],
            jnp.arange(c)[None, :, None], idx].add(a)

    return apply_op(fn, _t(x))


# -------------------------------------------------------- transposed conv


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, nd, op):
    st = tuple(stride) if isinstance(stride, (list, tuple)) else (
        stride,) * nd
    pd = tuple(padding) if isinstance(padding, (list, tuple)) else (
        padding,) * nd
    dl = tuple(dilation) if isinstance(dilation, (list, tuple)) else (
        dilation,) * nd
    opad = (tuple(output_padding)
            if isinstance(output_padding, (list, tuple))
            else (output_padding,) * nd)
    if groups != 1:
        raise NotImplementedError(f"{op}: groups > 1 not supported")
    dn_str = {1: ("NCH", "IOH", "NCH"), 3: ("NCDHW", "IODHW", "NCDHW")}[nd]
    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])

    def fn(a, w, *b):
        pads = tuple(
            (dl[i] * (w.shape[2 + i] - 1) - pd[i],
             dl[i] * (w.shape[2 + i] - 1) - pd[i] + opad[i])
            for i in range(nd))
        out = jax.lax.conv_general_dilated(
            a, jnp.flip(w, axis=tuple(range(2, 2 + nd))),
            window_strides=(1,) * nd, padding=pads,
            lhs_dilation=st, rhs_dilation=dl,
            dimension_numbers=dn_str)
        if b:
            out = out + b[0].reshape((1, -1) + (1,) * nd)
        return out

    return apply_op(fn, *args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL"):
    """Reference: paddle.nn.functional.conv1d_transpose (weight
    [in, out, k], fractionally-strided conv via lhs_dilation)."""
    return _conv_transpose(x, weight, bias, stride, padding,
                           output_padding, dilation, groups, 1,
                           "conv1d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW"):
    return _conv_transpose(x, weight, bias, stride, padding,
                           output_padding, dilation, groups, 3,
                           "conv3d_transpose")


# ------------------------------------------------------------- pixel ops


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = int(upscale_factor)

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        return a.transpose(0, 1, 4, 2, 5, 3).reshape(
            n, c // (r * r), h * r, w * r)

    return apply_op(fn, _t(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = int(downscale_factor)

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        return a.transpose(0, 1, 3, 5, 2, 4).reshape(
            n, c * r * r, h // r, w // r)

    return apply_op(fn, _t(x))


def channel_shuffle(x, groups, data_format="NCHW"):
    g = int(groups)

    def fn(a):
        n, c = a.shape[:2]
        rest = a.shape[2:]
        return a.reshape((n, g, c // g) + rest).swapaxes(1, 2).reshape(
            a.shape)

    return apply_op(fn, _t(x))


# ----------------------------------------------------------- activations


def log_sigmoid(x):
    return apply_op(jax.nn.log_sigmoid, _t(x))


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True):
    """Randomized leaky ReLU: slope ~ U[lower, upper] per element in
    training, the mean slope in eval (reference: F.rrelu)."""
    if training:
        key = _random.op_key()

        def fn(a):
            slope = jax.random.uniform(key, a.shape, jnp.float32,
                                       lower, upper).astype(a.dtype)
            return jnp.where(a >= 0, a, a * slope)
    else:
        mid = (lower + upper) / 2.0

        def fn(a):
            return jnp.where(a >= 0, a, a * mid)

    return apply_op(fn, _t(x))


def maxout(x, groups, axis=1):
    def fn(a):
        ax = axis % a.ndim  # negative axes wrap (paddle allows)
        c = a.shape[ax]
        pre = a.shape[:ax]
        post = a.shape[ax + 1:]
        a = a.reshape(pre + (c // groups, groups) + post)
        return a.max(axis=ax + 1)

    return apply_op(fn, _t(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    key = _random.op_key()

    def fn(a):
        g = jax.random.gumbel(key, a.shape, jnp.float32).astype(a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            onehot = jax.nn.one_hot(
                jnp.argmax(y, axis=axis), a.shape[axis], dtype=a.dtype,
                axis=axis)
            y = onehot + y - jax.lax.stop_gradient(y)  # straight-through
        return y

    return apply_op(fn, _t(x))


# -------------------------------------------------------- norms / misc


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    return apply_op(
        lambda a, b: jnp.power(
            jnp.sum(jnp.power(jnp.abs(a - b + epsilon), p), axis=-1,
                    keepdims=keepdim), 1.0 / p), _t(x), _t(y))


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    n = int(size)

    def fn(a):
        sq = jnp.square(a)
        half = n // 2
        pad_width = [(0, 0)] * a.ndim
        pad_width[1] = (half, n - half - 1)
        padded = jnp.pad(sq, pad_width)
        acc = sum(padded[:, i:i + a.shape[1]] for i in range(n))
        return a / jnp.power(k + alpha * acc / n, beta)

    return apply_op(fn, _t(x))


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  eps=1e-5, data_format="NCHW"):
    args = [_t(x)]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(_t(weight))
    if has_b:
        args.append(_t(bias))

    def fn(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = a.mean(axis=axes, keepdims=True)
        var = a.var(axis=axes, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + eps)
        shape = (1, -1) + (1,) * (a.ndim - 2)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    return apply_op(fn, *args)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    """Drop whole channels of a 5-D input (reference: F.dropout3d)."""
    if not training or p == 0.0:
        return _t(x)
    key = _random.op_key()

    def fn(a):
        keep = jax.random.bernoulli(
            key, 1.0 - p, a.shape[:2]).astype(a.dtype)
        return a * keep[..., None, None, None] / (1.0 - p)

    return apply_op(fn, _t(x))


def alpha_dropout(x, p=0.5, training=True):
    """SELU-preserving dropout (reference: F.alpha_dropout)."""
    if not training or p == 0.0:
        return _t(x)
    key = _random.op_key()
    alpha_p = -1.7580993408473766  # -scale * alpha of SELU
    a_coef = (1.0 - p) + p * alpha_p ** 2
    a_coef = 1.0 / np.sqrt(a_coef)
    b_coef = -a_coef * p * alpha_p

    def fn(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(
            a.dtype)

    return apply_op(fn, _t(x))


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW"):
    from .common import interpolate

    return interpolate(x, size=size, scale_factor=scale_factor,
                       mode=mode, align_corners=align_corners,
                       data_format=data_format)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0,
         dilations=1):
    """col2im — the inverse of unfold: accumulate [N, C*kh*kw, L] patch
    columns back into [N, C, H, W] (reference: F.fold)."""
    oh, ow = (output_sizes if isinstance(output_sizes, (list, tuple))
              else (output_sizes, output_sizes))
    kh, kw = (kernel_sizes if isinstance(kernel_sizes, (list, tuple))
              else (kernel_sizes, kernel_sizes))
    sh, sw = (strides if isinstance(strides, (list, tuple))
              else (strides, strides))
    ph, pw = (paddings if isinstance(paddings, (list, tuple))
              else (paddings, paddings))
    dh, dw = (dilations if isinstance(dilations, (list, tuple))
              else (dilations, dilations))

    def fn(a):
        n, ckk, l = a.shape
        c = ckk // (kh * kw)
        nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        cols = a.reshape(n, c, kh, kw, nh, nw)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[
                    :, :,
                    i * dh:i * dh + nh * sh:sh,
                    j * dw:j * dw + nw * sw:sw].add(cols[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]

    return apply_op(fn, _t(x))


# ---------------------------------------------------------------- losses


def huber_loss(input, label, delta=1.0, reduction="mean"):
    def fn(a, b):
        d = a - b
        absd = jnp.abs(d)
        val = jnp.where(absd <= delta, 0.5 * d * d,
                        delta * (absd - 0.5 * delta))
        return _reduce(val, reduction)

    return apply_op(fn, _t(input), _t(label))


def soft_margin_loss(input, label, reduction="mean"):
    def fn(a, y):
        return _reduce(jnp.log1p(jnp.exp(-y * a)), reduction)

    return apply_op(fn, _t(input), _t(label))


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean"):
    args = [_t(input), _t(label)] + ([_t(weight)]
                                     if weight is not None else [])

    def fn(a, y, *w):
        per = -(y * jax.nn.log_sigmoid(a)
                + (1 - y) * jax.nn.log_sigmoid(-a))
        if w:
            per = per * w[0]
        return _reduce(per.mean(axis=-1), reduction)

    return apply_op(fn, *args)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean"):
    args = [_t(input), _t(label)] + ([_t(weight)]
                                     if weight is not None else [])

    def fn(a, y, *w):
        gold = jnp.take_along_axis(a, y[:, None].astype(jnp.int32),
                                   axis=-1)
        diff = jnp.maximum(margin - gold + a, 0.0) ** p
        mask = 1.0 - jax.nn.one_hot(y, a.shape[-1], dtype=a.dtype)
        per = jnp.sum(diff * mask, -1) / a.shape[-1]
        if w:  # per-class weights indexed by the gold label
            per = per * w[0][y.astype(jnp.int32)]
        return _reduce(per, reduction)

    return apply_op(fn, *args)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    def fn(a, y):
        val = jnp.where(y > 0, a, jnp.maximum(margin - a, 0.0))
        return _reduce(val, reduction)

    return apply_op(fn, _t(input), _t(label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.power(
                jnp.sum(jnp.power(jnp.abs(u - v + epsilon), p), -1),
                1.0 / p)

        dp = dist(a, pos)
        dn = dist(a, neg)
        if swap:
            dn = jnp.minimum(dn, dist(pos, neg))
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply_op(fn, _t(input), _t(positive), _t(negative))


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean"):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative,
                                   margin=margin, swap=swap,
                                   reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dpn = distance_function(positive, negative)
        dn = apply_op(jnp.minimum, dn, dpn)
    return apply_op(
        lambda a, b: _reduce(jnp.maximum(a - b + margin, 0.0), reduction),
        dp, dn)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean"):
    def fn(a, y):
        if log_input:
            val = jnp.exp(a) - y * a
        else:
            val = a - y * jnp.log(a + epsilon)
        if full:
            stirling = (y * jnp.log(y + epsilon) - y
                        + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon)))
            val = val + jnp.where(y > 1, stirling, 0.0)
        return _reduce(val, reduction)

    return apply_op(fn, _t(input), _t(label))


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    def fn(a, y, var):
        var = jnp.maximum(var, epsilon)
        val = 0.5 * (jnp.log(var) + (a - y) ** 2 / var)
        if full:
            val = val + 0.5 * jnp.log(2 * jnp.asarray(jnp.pi))
        return _reduce(val, reduction)

    return apply_op(fn, _t(input), _t(label), _t(variance))
