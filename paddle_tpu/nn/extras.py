"""Layer-surface tail (r5; reference: python/paddle/nn/layer/ — the ~40
wrappers earlier rounds skipped). Thin Layers over the functional core;
anything with state (SpectralNorm's power-iteration vector, the conv
transposes' weights) manages it here."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = [
    "MaxPool3D", "AvgPool3D", "AdaptiveAvgPool3D", "AdaptiveMaxPool1D",
    "MaxUnPool1D", "MaxUnPool2D",
    "Conv1DTranspose", "Conv3DTranspose",
    "PixelShuffle", "PixelUnshuffle", "ChannelShuffle",
    "LogSigmoid", "RReLU", "Maxout", "GumbelSoftmax", "Softmax2D",
    "PairwiseDistance", "LocalResponseNorm", "InstanceNorm1D",
    "InstanceNorm3D", "Dropout3D", "AlphaDropout",
    "Pad1D", "Pad3D", "ZeroPad2D", "Unflatten", "Unfold", "Fold",
    "Upsample", "UpsamplingNearest2D", "UpsamplingBilinear2D",
    "HuberLoss", "SoftMarginLoss", "MultiLabelSoftMarginLoss",
    "MultiMarginLoss", "HingeEmbeddingLoss", "TripletMarginLoss",
    "TripletMarginWithDistanceLoss", "PoissonNLLLoss", "GaussianNLLLoss",
    "CTCLoss", "LayerDict", "SpectralNorm",
]


class _Fwd(Layer):
    """Base for stateless wrappers: subclasses set _fn + captured kwargs."""

    def extra_repr(self):
        return ", ".join(f"{k}={v}" for k, v in self._kw.items())


def _stateless(name, ffn, params):
    """Build a Layer class whose forward calls ``ffn(x, **captured)``."""

    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        kw = dict(zip(params, args))
        kw.update(kwargs)
        kw.pop("name", None)
        self._kw = kw

    def forward(self, x, *extra):
        return ffn(x, *extra, **self._kw)

    return type(name, (_Fwd,), {"__init__": __init__, "forward": forward,
                                "__doc__": f"paddle.nn.{name} (thin "
                                           f"wrapper over F.{ffn.__name__})"})


MaxPool3D = _stateless("MaxPool3D", F.max_pool3d,
                       ["kernel_size", "stride", "padding"])
AvgPool3D = _stateless("AvgPool3D", F.avg_pool3d,
                       ["kernel_size", "stride", "padding"])
AdaptiveAvgPool3D = _stateless("AdaptiveAvgPool3D", F.adaptive_avg_pool3d,
                               ["output_size"])
AdaptiveMaxPool1D = _stateless("AdaptiveMaxPool1D", F.adaptive_max_pool1d,
                               ["output_size"])
MaxUnPool1D = _stateless("MaxUnPool1D", F.max_unpool1d, ["kernel_size",
                                                         "stride"])
MaxUnPool2D = _stateless("MaxUnPool2D", F.max_unpool2d, ["kernel_size",
                                                         "stride"])
PixelShuffle = _stateless("PixelShuffle", F.pixel_shuffle,
                          ["upscale_factor"])
PixelUnshuffle = _stateless("PixelUnshuffle", F.pixel_unshuffle,
                            ["downscale_factor"])
ChannelShuffle = _stateless("ChannelShuffle", F.channel_shuffle,
                            ["groups"])
LogSigmoid = _stateless("LogSigmoid", F.log_sigmoid, [])
Maxout = _stateless("Maxout", F.maxout, ["groups", "axis"])
PairwiseDistance = _stateless("PairwiseDistance", F.pairwise_distance,
                              ["p", "epsilon", "keepdim"])
LocalResponseNorm = _stateless("LocalResponseNorm", F.local_response_norm,
                               ["size", "alpha", "beta", "k"])
Unfold = _stateless("Unfold", F.unfold,
                    ["kernel_sizes", "strides", "paddings", "dilations"])
Fold = _stateless("Fold", F.fold,
                  ["output_sizes", "kernel_sizes", "strides", "paddings",
                   "dilations"])
HuberLoss = _stateless("HuberLoss", F.huber_loss, ["delta", "reduction"])
SoftMarginLoss = _stateless("SoftMarginLoss", F.soft_margin_loss,
                            ["reduction"])
MultiLabelSoftMarginLoss = _stateless(
    "MultiLabelSoftMarginLoss", F.multi_label_soft_margin_loss,
    ["weight", "reduction"])
MultiMarginLoss = _stateless("MultiMarginLoss", F.multi_margin_loss,
                             ["p", "margin", "weight", "reduction"])
HingeEmbeddingLoss = _stateless("HingeEmbeddingLoss",
                                F.hinge_embedding_loss,
                                ["margin", "reduction"])
PoissonNLLLoss = _stateless("PoissonNLLLoss", F.poisson_nll_loss,
                            ["log_input", "full", "epsilon", "reduction"])


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._kw = dict(margin=margin, p=p, epsilon=epsilon, swap=swap,
                        reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative,
                                     **self._kw)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self._kw = dict(margin=margin, swap=swap, reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative,
            distance_function=self.distance_function, **self._kw)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self._kw = dict(full=full, epsilon=epsilon, reduction=reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, **self._kw)


class CTCLoss(Layer):
    """Reference: paddle.nn.CTCLoss over warpctc — here the functional
    log-domain alpha recursion (nn.functional.ctc_loss)."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class GumbelSoftmax(Layer):
    def __init__(self, temperature=1.0, hard=False, axis=-1, name=None):
        super().__init__()
        self._kw = dict(temperature=temperature, hard=hard, axis=axis)

    def forward(self, x):
        return F.gumbel_softmax(x, **self._kw)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format=None, name=None):
        super().__init__()
        self.padding = (list(padding) if isinstance(padding, (list, tuple))
                        else [padding] * self._width)
        self.mode, self.value = mode, value
        self.data_format = data_format or self._fmt

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadNd):
    _width, _fmt = 2, "NCL"


class Pad3D(_PadNd):
    _width, _fmt = 6, "NCDHW"


class ZeroPad2D(_PadNd):
    _width, _fmt = 4, "NCHW"

    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, mode="constant", value=0.0,
                         data_format=data_format)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, list(shape)

    def forward(self, x):
        shp = self.shape

        def fn(a):
            ax = self.axis % a.ndim  # negative axes wrap (paddle allows)
            pre = a.shape[:ax]
            post = a.shape[ax + 1:]
            return a.reshape(pre + tuple(shp) + post)

        return apply_op(fn, x if isinstance(x, Tensor) else Tensor(x))


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self._kw = dict(size=size, scale_factor=scale_factor, mode=mode,
                        align_corners=align_corners,
                        data_format=data_format)

    def forward(self, x):
        return F.upsample(x, **self._kw)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size=size, scale_factor=scale_factor,
                         mode="nearest", data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size=size, scale_factor=scale_factor,
                         mode="bilinear", align_corners=True,
                         data_format=data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = (None if weight_attr is False else
                       self.create_parameter(
                           (num_features,), attr=weight_attr,
                           default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True))

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr)


class _ConvTransposeNd(Layer):
    _nd = None

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format=None,
                 name=None):
        super().__init__()
        nd = self._nd
        ks = (tuple(kernel_size) if isinstance(kernel_size, (list, tuple))
              else (kernel_size,) * nd)
        fan_in = in_channels * int(np.prod(ks))
        bound = 1.0 / float(np.sqrt(fan_in))
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups) + ks, attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True))
        self._kw = dict(stride=stride, padding=padding,
                        output_padding=output_padding, dilation=dilation,
                        groups=groups)

    def forward(self, x, output_size=None):
        fn = (F.conv1d_transpose if self._nd == 1 else F.conv3d_transpose)
        return fn(x, self.weight, self.bias, output_size=output_size,
                  **self._kw)


class Conv1DTranspose(_ConvTransposeNd):
    _nd = 1


class Conv3DTranspose(_ConvTransposeNd):
    _nd = 3


class LayerDict(Layer):
    """Dict-style sublayer container (reference: paddle.nn.LayerDict)."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        setattr(self, key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = (sublayers.items() if isinstance(sublayers, dict)
                 else sublayers)
        for k, v in items:
            self[k] = v

    def pop(self, key):
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer

    def clear(self):
        self._sub_layers.clear()


class SpectralNorm(Layer):
    """Spectral normalization of a weight (reference:
    paddle.nn.SpectralNorm): one power iteration per forward against
    persistent u/v buffers estimates sigma_max; returns weight / sigma."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        rng = np.random.default_rng(0)

        def _unit(n):
            v = rng.standard_normal(n).astype(np.float32)
            return v / (np.linalg.norm(v) + epsilon)

        self.register_buffer("weight_u", Tensor(_unit(h)))
        self.register_buffer("weight_v", Tensor(_unit(w)))

    def forward(self, weight):
        dim, eps, iters = self.dim, self.epsilon, self.power_iters
        wt = weight if isinstance(weight, Tensor) else Tensor(weight)

        # power iteration ONCE, untaped (u/v are frozen in the standard
        # SN gradient); the taped part is only the cheap sigma matvec +
        # division, through which the weight gradient flows
        mat0 = jnp.moveaxis(wt._data, dim, 0).reshape(
            wt._data.shape[dim], -1)
        u, v = self.weight_u._data, self.weight_v._data
        for _ in range(iters):
            v = mat0.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat0 @ v
            u = u / (jnp.linalg.norm(u) + eps)
        self.weight_u._data = u
        self.weight_v._data = v

        def fn(w):
            mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            sigma = u @ mat @ v
            return w / sigma

        return apply_op(fn, wt)

