"""Gradient clipping (reference: python/paddle/nn/clip.py —
ClipGradByGlobalNorm/ByNorm/ByValue).

In hybrid-parallel runs the global-norm reduction must span every model-/
pipeline-/sharding-group (reference: HybridParallelOptimizer's distributed
ClipGradByGlobalNorm); inside one compiled SPMD step that is a plain psum —
the distributed trainer handles it. These classes implement the eager path.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue", "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _global_sq_norm(self, params_grads):
        """Σ‖g‖² (overridden by variants, e.g. the MoE expert-aware clip)."""
        sq = None
        for _, g in params_grads:
            if g is None:
                continue
            s = jnp.sum(jnp.square(g._data.astype(jnp.float32)))
            sq = s if sq is None else sq + s
        return sq

    def _apply_scale(self, params_grads, global_norm):
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-6),
                            1.0)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor._wrap(
                    (g._data.astype(jnp.float32) * scale).astype(g.dtype))))
        return out

    def __call__(self, params_grads):
        sq = self._global_sq_norm(params_grads)
        if sq is None:
            return params_grads
        return self._apply_scale(params_grads, jnp.sqrt(sq))

    # functional variant for the compiled trainer
    @staticmethod
    def apply_to_tree(grads_tree, clip_norm):
        import jax

        leaves = jax.tree_util.tree_leaves(grads_tree)
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        gn = jnp.sqrt(sq)
        scale = jnp.minimum(clip_norm / jnp.maximum(gn, 1e-6), 1.0)
        return jax.tree_util.tree_map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads_tree), gn


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            n = jnp.linalg.norm(g._data.astype(jnp.float32))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-6), 1.0)
            out.append((p, Tensor._wrap((g._data * scale).astype(g.dtype))))
        return out


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(-max if min is None else min)

    def __call__(self, params_grads):
        return [
            (p, g if g is None else Tensor._wrap(jnp.clip(g._data, self.min, self.max)))
            for p, g in params_grads
        ]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(0.0)
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(p.grad._data)) for p in params]))
    else:
        total = jnp.sum(jnp.stack([
            jnp.sum(jnp.abs(p.grad._data.astype(jnp.float32)) ** norm_type) for p in params
        ])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        p.grad._data = (p.grad._data * scale).astype(p.grad.dtype)
    return Tensor._wrap(total)
