"""Deterministic, seed-driven fault injection for the serving engine
(ISSUE 6 tentpole, part 4).

A fault-tolerance layer is only as trustworthy as the failures it has
actually survived, so the engine carries NAMED injection points — host-side
hook sites the scheduler consults between dispatches — and this module
supplies the plan that decides when each one fires. Everything is
deterministic: a plan is a spec string plus a seed, firing decisions come
from per-point check counters and a counter-keyed PCG64 stream (never wall
clock, never global RNG state), so a chaos test that fails replays
identically under the same spec.

Injection points (the engine's hook sites; see README "Failure semantics"):

* ``pool-exhaustion``  — ``_ensure_pages`` pretends the page pool is empty,
  driving the shrink-chain → preempt → bounded-retry path.
* ``step-exception``   — raises ``InjectedFault`` inside ONE request's
  per-request harvest block, proving isolation (request FAILED, batch
  lives).
* ``nan-logits``       — forces the request's NaN/inf logit-guard flag, as
  if the model had produced non-finite logits for that row.
* ``drafter-corruption`` — the spec-decode drafter raises (default) or its
  proposed tokens are corrupted (``corrupt=1``), driving the zero-draft
  fallback / rejection machinery.
* ``slow-step``        — sleeps ``delay_ms`` at the top of ``step()``,
  driving deadline/TTL expiry deterministically.
* ``prefix-cache-corruption`` — flips one cached page's device bytes at a
  prefix-cache hit (when the page is idle; an in-use page is never
  corrupted by the harness) and signals doubt: the cache invalidates the
  page and every descendant block, the admission recomputes from scratch,
  and the corruption is provably isolated to a cache MISS — never a wrong
  token (ISSUE 8).

Multi-replica serving points (ISSUE 13 — consulted by the
``serving/router.py`` supervisor loop; ``rid`` selects the REPLICA
index here, reusing the per-request key):

* ``replica-crash``    — kills/poisons the chosen replica at the k-th
  supervisor tick (``rid=<replica index>`` picks the victim, ``at=k``
  the tick): an in-process replica's engine thread vanishes without
  finishing its tickets, a subprocess replica takes SIGKILL. Drives
  crash detection → mid-stream migration → supervised restart.
* ``heartbeat-drop``   — the chosen replica's heartbeat probe reports
  failure while the replica itself stays up, driving the
  false-positive/slow-network arm of crash detection: the router must
  still migrate (and the cancel-before-resume path must keep the
  client stream bit-identical).

Training points (ISSUE 7 — consulted by ``distributed/checkpoint.py``,
``distributed/ckpt_manager.py`` and the ``hapi.Model.fit`` train loop):

* ``ckpt-io-error``    — the checkpoint writer raises ``OSError`` before a
  staging-file write, leaving a TORN ``.tmp-*`` dir; the committed
  checkpoint at the final path must be unaffected (atomic-commit proof).
* ``slow-ckpt-write``  — sleeps ``delay_ms`` at the top of the checkpoint
  writer, driving async-overlap and preemption-grace-budget paths.
* ``train-step-exception`` — raises ``InjectedFault`` at the top of one
  training step (a transient dispatch fault), driving the bounded
  retry-with-backoff path.
* ``train-nan-loss``   — forces the step's scalar loss to NaN, driving the
  divergence guard's rollback-to-last-good + skip-batch path.
* ``preempt-signal``   — trips the preemption flag at a step boundary, as
  if SIGTERM had arrived: the loop drains the step, force-commits a final
  checkpoint, and raises ``TrainingPreempted``.

Silent-data-corruption points (ISSUE 14 — the bit-flip family. These
damage data WITHOUT signaling doubt; the seed-driven offset/bit choice
comes from the point's own PCG64 stream via :meth:`FaultPlan.draw`, so a
failing chaos run replays the exact same flipped bit):

* ``bit-flip-weight`` — flips one bit of one weight element on device
  right before an ``IntegritySentinel`` weight-audit probe samples that
  shard slice. The audit's digest comparison must catch it; containment
  is the quarantine ladder (watchdog drops ``/readyz`` → the router
  migrates streams off → supervised restart with verified weights).
* ``bit-flip-kv``     — corrupts a matched, idle cached KV page's device
  bytes at a prefix-cache hit WITHOUT invalidating it (contrast
  ``prefix-cache-corruption``, which signals doubt): only the per-page
  checksum probe at splice time stands between the flip and a wrong
  token. Detection costs a cache miss, never a token.
* ``bit-flip-ckpt``   — flips one seed-chosen bit of one seed-chosen
  data file in the checkpoint staging dir after the content digests are
  recorded but before the commit markers land: the checkpoint COMMITS
  (completeness says nothing about content), and only the load-time
  digest verification can refuse it — ``CheckpointManager.restore``
  must fall back to the newest step that verifies.

KV host-tier points (ISSUE 15 — consulted by the ``kv_tier.HostTier``
spill worker, the background thread that copies demoted prefix-cache
pages device→host and back):

* ``kv-spill-corrupt`` — flips one seed-chosen byte of a HOST-resident
  demoted page right before a promotion reads it, with no doubt signal
  (host DRAM bit rot). The promote-time blake2b compare against the
  demotion-time digest must catch it; containment is invalidate +
  recompute-as-miss — the corrupt bytes never reach the device pool,
  so detection costs a cache miss, never a token.
* ``slow-host-copy``  — sleeps ``delay_ms`` (default 25) at the top of
  each spill-worker job, stretching the demote/promote window: lookups
  that land inside it must degrade to misses (partial-prefill
  recompute), never stall the engine thread or deadlock the tier.
* ``racey-worker-write`` — the spill worker writes an engine-owned
  ``HostTier`` counter directly (via ``setattr``, so the static
  tpurace pass cannot see it — ISSUE 19), bypassing the job-queue/
  completion-deque channel. With ``ownership_guard()`` armed the write
  raises ``OwnershipError`` inside the worker's isolation, routes
  through ``_post_fault``, and the engine drain contains the job as a
  counted drop; guard off, the write is a value-identical no-op — the
  differential is the chaos suite's proof the runtime guard catches
  what the linter cannot.

Cluster KV-handoff points (ISSUE 20 — consulted on the cluster
coordinator's handoff thread; the stall lands at the start of the
shipment, the corruption between the prefill replica's export and the
decode replica's import):

* ``kv-handoff-corrupt`` — flips one seed-chosen byte of one shipped
  page's bytes while the payload is in transit between replicas, with
  no doubt signal (a NIC/DMA flip). The decode-side per-page blake2b
  verify in ``Engine.adopt_kv_pages`` must catch it and truncate the
  adoption at the corrupt block; the stream falls back to
  resume-from-emitted recompute for the unverified suffix — chaos
  asserts the delivered tokens stay bit-identical.
* ``kv-handoff-stall``   — sleeps ``delay_ms`` (default 50) at the top
  of the handoff thread, simulating a slow source/transfer. A stall
  past the cluster's ``handoff_budget_s`` abandons the shipment (the
  decode placement proceeds as plain recompute); under budget it just
  stretches the window — which is also how chaos holds the handoff
  open to SIGKILL the prefill replica mid-shipment. Either way: no
  deadlock, no stall of either engine thread, bit-identical stream.

Spec grammar (``FLAGS_fault_inject`` / env ``PADDLE_TPU_FAULT_INJECT`` /
``Engine(fault_plan=...)``)::

    point[:key=val[,key=val...]][;point2[:...]]

    nan-logits:rid=2,times=1
    pool-exhaustion:at=3,times=2;slow-step:every=1,delay_ms=30
    step-exception:rate=0.01,seed=7

Per-point keys — all optional, combined with AND semantics:

* ``rid=N``      — only checks on behalf of request id N are eligible.
* ``at=N``       — fire exactly on the N-th eligible check (1-based).
* ``every=N``    — fire on every N-th eligible check.
* ``rate=P``     — fire with probability P per eligible check, from the
  plan's seeded stream (deterministic given the check order).
* ``times=M``    — stop firing after M fires (unbounded if absent).
* ``seed=S``     — per-point seed override (default: plan seed).
* ``delay_ms=F`` — slow-step sleep duration (default 20 ms).
* ``corrupt=1``  — drafter-corruption corrupts proposed tokens instead of
  raising.

With none of ``at``/``every``/``rate`` given, the point fires on every
eligible check.
"""
from __future__ import annotations

import zlib
from typing import Dict, Optional

import numpy as np

__all__ = ["POINTS", "FaultPlan", "InjectedFault", "plan_from_flags"]

POINTS = (
    "pool-exhaustion",
    "step-exception",
    "nan-logits",
    "drafter-corruption",
    "slow-step",
    "prefix-cache-corruption",
    # training-resilience points (ISSUE 7)
    "ckpt-io-error",
    "train-step-exception",
    "train-nan-loss",
    "preempt-signal",
    "slow-ckpt-write",
    # multi-replica serving points (ISSUE 13 — consulted by
    # serving/router.py's supervisor loop and Replica.heartbeat)
    "replica-crash",
    "heartbeat-drop",
    # silent-data-corruption points (ISSUE 14 — the damage is SILENT:
    # unlike prefix-cache-corruption nothing signals doubt, so only the
    # integrity layer's digests/checksums/shadow recompute can catch it)
    "bit-flip-weight",
    "bit-flip-kv",
    "bit-flip-ckpt",
    # KV host-tier points (ISSUE 15 — consulted ONLY on the spill
    # worker thread, so chaos replays stay deterministic)
    "kv-spill-corrupt",
    "slow-host-copy",
    # thread-ownership point (ISSUE 19 — consulted on the spill worker
    # thread; pairs with analysis.runtime.ownership_guard)
    "racey-worker-write",
    # cluster KV-handoff points (ISSUE 20 — consulted on the cluster's
    # handoff thread between the prefill export and the decode import)
    "kv-handoff-corrupt",
    "kv-handoff-stall",
)


class InjectedFault(RuntimeError):
    """The exception injected at ``step-exception`` / ``drafter-corruption``
    sites — a deliberately FOREIGN type (not a taxonomy error), so chaos
    tests prove the engine's broad wrap-into-taxonomy path, not just its
    handling of its own exception classes."""


class _Point:
    """One injection point's config + deterministic firing state."""

    __slots__ = ("name", "params", "checks", "fires", "_rng")

    def __init__(self, name: str, params: Dict[str, float], seed: int):
        self.name = name
        self.params = params
        self.checks = 0  # eligible checks seen
        self.fires = 0   # times actually fired
        # counter-keyed stream: (plan-or-point seed) x crc32(point name)
        # — stable across processes, independent across points
        pseed = int(params.get("seed", seed))
        self._rng = np.random.Generator(
            np.random.PCG64([pseed, zlib.crc32(name.encode())]))

    def fire(self, rid: Optional[int]) -> bool:
        p = self.params
        want_rid = p.get("rid")
        if want_rid is not None and (rid is None or int(want_rid) != rid):
            return False
        self.checks += 1
        if "times" in p and self.fires >= int(p["times"]):
            return False
        hit = True
        if "at" in p:
            hit = hit and self.checks == int(p["at"])
        if "every" in p:
            hit = hit and self.checks % int(p["every"]) == 0
        if "rate" in p:
            # draw unconditionally so the stream position depends only on
            # the check index, never on which other keys matched
            draw = float(self._rng.random())
            hit = hit and draw < float(p["rate"])
        if hit:
            self.fires += 1
        return hit


class FaultPlan:
    """A parsed fault-injection plan. The engine calls ``fire(point, rid)``
    at each hook site; everything else is introspection for tests."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self._points: Dict[str, _Point] = {}
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            name, _, rest = clause.partition(":")
            name = name.strip()
            if name not in POINTS:
                raise ValueError(
                    f"unknown fault-injection point {name!r}; expected one "
                    f"of {', '.join(POINTS)}")
            params: Dict[str, float] = {}
            for kv in filter(None, (s.strip() for s in rest.split(","))):
                k, _, v = kv.partition("=")
                if not _:
                    raise ValueError(
                        f"malformed fault-injection param {kv!r} "
                        f"(expected key=value)")
                params[k.strip()] = float(v)
            self._points[name] = _Point(name, params, self.seed)

    @classmethod
    def from_spec(cls, spec, seed: int = 0) -> Optional["FaultPlan"]:
        """Coerce ``None`` / empty string / an existing plan / a spec
        string into a plan (or None). The engine's single entry point."""
        if spec is None or isinstance(spec, FaultPlan):
            return spec
        spec = str(spec).strip()
        return cls(spec, seed=seed) if spec else None

    def fire(self, point: str, rid: Optional[int] = None) -> bool:
        """Should ``point`` fault on this check? Deterministic in the
        sequence of calls; counts fires for ``fired()`` and the
        ``paddle_tpu_faults_injected_total{point}`` counter.

        A name outside the :data:`POINTS` registry RAISES (ISSUE 14
        satellite): a typo'd point in a hook site or a chaos test used
        to return False forever, so the test asserted "no fault fired"
        against an injection that never existed — vacuously green.
        Valid points simply absent from this plan still return False."""
        if point not in POINTS:
            raise ValueError(
                f"unregistered fault-injection point {point!r}; known "
                f"points: {', '.join(POINTS)} (add new points to "
                "testing.faultinject.POINTS so typos can never pass "
                "chaos tests vacuously)")
        st = self._points.get(point)
        if st is None:
            return False
        hit = st.fire(rid)
        if hit:
            self._count(point)
        return hit

    def draw(self, point: str, n: int) -> int:
        """A deterministic draw in ``[0, n)`` from ``point``'s seeded
        stream — the bit-flip family's offset/bit selector. Advances the
        same PCG64 stream ``rate=`` uses, so the choice is reproducible
        given the spec+seed and the sequence of calls."""
        if point not in POINTS:
            raise ValueError(
                f"unregistered fault-injection point {point!r}")
        st = self._points.get(point)
        if st is None or n <= 0:
            return 0
        return int(st._rng.integers(0, n))

    def param(self, point: str, key: str, default: float) -> float:
        st = self._points.get(point)
        if st is None:
            return default
        return float(st.params.get(key, default))

    def fired(self, point: str) -> int:
        st = self._points.get(point)
        return st.fires if st is not None else 0

    def active(self, point: str) -> bool:
        return point in self._points

    @staticmethod
    def _count(point: str):
        # observability is optional here: the harness must keep working
        # in stdlib-only contexts (tpulint fixtures, docs examples)
        try:
            from ..observability import counter
        except Exception:  # pragma: no cover - import-cycle safety net
            return
        counter("paddle_tpu_faults_injected_total",
                "fault-injection hook fires, by injection point",
                labelnames=("point",)).labels(point=point).inc()


def plan_from_flags() -> Optional[FaultPlan]:
    """The engine's default plan: ``FLAGS_fault_inject`` (which the env
    var ``PADDLE_TPU_FAULT_INJECT`` overrides at first read, per the
    flags registry contract)."""
    from ..framework import flags

    spec = flags.get_flags("FLAGS_fault_inject")["FLAGS_fault_inject"]
    return FaultPlan.from_spec(spec)
