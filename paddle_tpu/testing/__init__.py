"""paddle_tpu.testing — test-support utilities that ship in the package
(not under tests/) because production code cooperates with them: the
serving engine carries named fault-injection hook sites that
``faultinject.FaultPlan`` drives (ISSUE 6), the same way the chaos suite
and a staging deployment would.

Pure stdlib + numpy at import time; never pulls in jax.
"""
from .faultinject import POINTS, FaultPlan, InjectedFault, plan_from_flags

__all__ = ["FaultPlan", "InjectedFault", "POINTS", "plan_from_flags"]
