"""paddle.incubate parity surface (reference: python/paddle/incubate/) —
experimental fused layers + distributed models (MoE lands with the EP
milestone)."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401

__all__ = ["nn"]
from . import asp  # noqa: F401
