"""Megablocks-style ragged (grouped) expert compute for MoE.

Reference: the fused expert GEMM ``paddle/fluid/operators/fused/fused_moe_op``
computes each expert's FFN only over the tokens actually routed to it. The
capacity-padded GShard dispatch (moe_layer.py) instead materializes a dense
``[E, C, H]`` buffer and runs every expert over ``C`` rows whether or not
they are real tokens — with the default capacity factor 1.2 that is ~17%
wasted FLOPs, and much more when routing is unbalanced.

TPU-native equivalent (VERDICT r1 #6): sort the (token, choice) pairs by
expert and run ``jax.lax.ragged_dot`` — XLA's grouped GEMM over contiguous
row-groups — against the stacked expert weights. Identical numerics to the
dense path (same capacity-drop rule, same combine weights); dropped pairs
are computed-then-zeroed so gradients match exactly. A ``capacity=None``
mode gives dropless (megablocks) routing.

The ragged path is the no-expert-parallel fast path: inside an ``ep``-sharded
mesh the all-to-all needs static shapes, so the dense GShard dispatch stays
(see MoELayer._pure_forward).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["ragged_routing", "moe_ragged_ffn", "padded_flops_fraction"]


def ragged_routing(gate_idx, gate_val, num_expert: int,
                   capacity: Optional[int]):
    """Sort (token, choice) pairs by expert for grouped compute.

    Pairs are flattened COLUMN-major (all choice-0 pairs in token order,
    then choice-1, …) so the per-expert arrival rank — and therefore the
    capacity-drop rule — is identical to ``gshard_dispatch``'s sequential
    per-column counting.

    Returns ``(tok_sorted, e_sorted, w_sorted, group_sizes)``: the source
    token of each sorted pair, its expert, its combine weight (gate value,
    zeroed when dropped), and tokens-per-expert ``[E]``.
    """
    T, k = gate_idx.shape
    e_flat = gate_idx.T.reshape(-1)                      # [k*T]
    v_flat = gate_val.T.reshape(-1)
    tok_flat = jnp.tile(jnp.arange(T, dtype=jnp.int32), k)
    one = jax.nn.one_hot(e_flat, num_expert, dtype=jnp.int32)
    group_sizes = jnp.sum(one, axis=0)                   # [E]
    if capacity is not None:
        rank = jnp.sum(jnp.cumsum(one, axis=0) * one, axis=-1) - 1
        keep = rank < capacity
        v_flat = jnp.where(keep, v_flat, 0.0)
    order = jnp.argsort(e_flat, stable=True)
    return tok_flat[order], e_flat[order], v_flat[order], group_sizes


def moe_ragged_ffn(xt, gate_idx, gate_val, w1, b1, w2, b2, act,
                   capacity: Optional[int]):
    """Routed two-linear expert FFN via grouped GEMMs.

    ``xt`` [T, H]; ``w1`` [E, H, F], ``b1`` [E, F], ``w2`` [E, F, H],
    ``b2`` [E, H] (stacked expert params, paddle [in, out] weight layout —
    exactly ``ragged_dot``'s rhs orientation); ``act`` elementwise.
    ``capacity=None`` → dropless.
    """
    T, H = xt.shape
    tok_s, e_s, w_s, group_sizes = ragged_routing(
        gate_idx, gate_val, w1.shape[0], capacity
    )
    xs = xt[tok_s]                                        # [k*T, H]
    h = jax.lax.ragged_dot(xs, w1, group_sizes) + b1[e_s]
    ys = jax.lax.ragged_dot(act(h), w2, group_sizes) + b2[e_s]
    y = jnp.zeros((T, H), ys.dtype).at[tok_s].add(ys * w_s[:, None])
    return y


def padded_flops_fraction(num_expert: int, capacity: int, tokens: int,
                          top_k: int) -> float:
    """Fraction of the dense GShard path's expert FLOPs that are padding —
    what the ragged path saves. Dense computes ``E*C`` rows; ragged computes
    the ``k*T`` real (token, choice) pairs."""
    dense_rows = num_expert * capacity
    return max(0.0, 1.0 - (top_k * tokens) / dense_rows)
