"""Expert-parallel MoE (reference: python/paddle/incubate/distributed/models/
moe/)."""
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate
from .grad_clip import ClipGradForMOEByGlobalNorm
from .moe_layer import (
    ExpertFFN,
    MoELayer,
    count_by_gate,
    gshard_dispatch,
    limit_by_capacity,
)
from .ragged import moe_ragged_ffn, padded_flops_fraction, ragged_routing

__all__ = [
    "MoELayer", "ExpertFFN", "BaseGate", "NaiveGate", "GShardGate",
    "SwitchGate", "count_by_gate", "limit_by_capacity", "gshard_dispatch",
    "moe_ragged_ffn", "ragged_routing", "padded_flops_fraction",
    "ClipGradForMOEByGlobalNorm",
]
