"""Expert-parallel MoE (reference: python/paddle/incubate/distributed/models/
moe/)."""
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate
from .grad_clip import ClipGradForMOEByGlobalNorm
from .moe_layer import (
    MoELayer,
    count_by_gate,
    gshard_dispatch,
    limit_by_capacity,
)

__all__ = [
    "MoELayer", "BaseGate", "NaiveGate", "GShardGate", "SwitchGate",
    "count_by_gate", "limit_by_capacity", "gshard_dispatch",
    "ClipGradForMOEByGlobalNorm",
]
