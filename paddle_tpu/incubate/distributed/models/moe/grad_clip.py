"""Expert-aware global-norm clip (reference: python/paddle/incubate/
distributed/models/moe/grad_clip.py — ClipGradForMOEByGlobalNorm).

Expert parameters exist once per expert-parallel rank in the reference, so
their squared norms are divided by the moe group size before entering the
global norm (otherwise each replica would be double-counted). Single-
controller SPMD holds each expert exactly once, so the correction factor is
1 unless the caller supplies ``moe_group`` world size explicitly."""
from __future__ import annotations

import jax.numpy as jnp

from .....nn.clip import ClipGradByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]


def _is_expert(p) -> bool:
    return bool(getattr(p, "is_expert", False))


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm=1.0, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        super().__init__(clip_norm=clip_norm, group_name=group_name)
        self.is_expert = is_expert_param_func or _is_expert
        self.moe_world = getattr(moe_group, "nranks", 1) if moe_group else 1

    def _global_sq_norm(self, params_grads):
        sq_normal = None
        sq_expert = 0.0
        for p, g in params_grads:
            if g is None:
                continue
            s = jnp.sum(jnp.square(g._data.astype(jnp.float32)))
            if self.is_expert(p):
                sq_expert = sq_expert + s
            else:
                sq_normal = s if sq_normal is None else sq_normal + s
        if sq_normal is None and not isinstance(sq_expert, jnp.ndarray):
            return None
        return (0.0 if sq_normal is None else sq_normal) + (
            sq_expert / max(1, self.moe_world))
