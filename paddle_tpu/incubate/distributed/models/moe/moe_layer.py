"""MoE layer with expert parallelism (reference: python/paddle/incubate/
distributed/models/moe/moe_layer.py — MoELayer; utils.py — count_by_gate,
limit_by_capacity; and the static ops global_scatter/global_gather in
paddle/fluid/operators/collective/).

TPU-native design (SURVEY.md B16/C12): the reference routes tokens with an
explicit all-to-all keyed by per-expert counts (``global_scatter``). The
GSPMD formulation replaces count bookkeeping with the GShard
dispatch/combine einsum over a *capacity* dimension:

    dispatch [T, E, C]  one-hot: token t → slot c of expert e
    expert_in = einsum('tec,th->ech', dispatch, x)      # the all-to-all
    expert_out[e] = expert_e(expert_in[e])              # vmapped experts
    y = einsum('tec,ech->th', combine, expert_out)      # the return a2a

Expert weights are stacked ``[E, …]`` and sharded over the expert-parallel
mesh axis; annotating ``expert_in`` as ``P('ep', …)``-sharded makes XLA
insert exactly the all-to-all the reference hand-codes. Tokens that overflow
an expert's capacity are dropped (zero contribution) — identical semantics
to the reference's ``limit_by_capacity``.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..... import nn
from .....framework.tensor import Tensor, apply_op, pause_tape
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate
from .ragged import moe_ragged_ffn, padded_flops_fraction

__all__ = ["MoELayer", "ExpertFFN", "gshard_dispatch", "count_by_gate",
           "limit_by_capacity"]

_ACT_FNS = {
    "relu": jax.nn.relu,
    "gelu": lambda a: jax.nn.gelu(a, approximate=False),
    "silu": jax.nn.silu,
}


class ExpertFFN(nn.Layer):
    """Canonical two-linear expert (what the reference's fused_moe_op
    computes). When every expert of a MoELayer is an ExpertFFN with the same
    activation and no expert-parallel sharding is active, MoELayer takes the
    ragged grouped-GEMM path (ragged.py) instead of capacity-padded dense
    compute."""

    def __init__(self, d_model: int, d_hidden: int, activation: str = "gelu"):
        super().__init__()
        if activation not in _ACT_FNS:
            raise ValueError(f"unsupported ExpertFFN activation {activation!r}")
        self.fc1 = nn.Linear(d_model, d_hidden)
        self.fc2 = nn.Linear(d_hidden, d_model)
        self.activation = activation

    def forward(self, x):
        from .....nn import functional as F

        return self.fc2(getattr(F, self.activation)(self.fc1(x)))


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def count_by_gate(topk_idx, num_expert: int):
    """Tokens per expert (reference: utils.count_by_gate)."""
    idx = _unwrap(topk_idx)
    one = jax.nn.one_hot(idx.reshape(-1), num_expert, dtype=jnp.int32)
    return jnp.sum(one, axis=0)


def limit_by_capacity(topk_idx, num_expert: int, capacity: int):
    """Mask assignments beyond each expert's capacity, preserving order
    (reference: utils.limit_by_capacity). Returns (masked_idx, position)
    where masked slots hold -1."""
    idx = _unwrap(topk_idx).reshape(-1)
    one = jax.nn.one_hot(idx, num_expert, dtype=jnp.int32)
    pos_in_expert = jnp.cumsum(one, axis=0) * one  # 1-based rank per expert
    pos = jnp.sum(pos_in_expert, axis=-1) - 1      # 0-based position
    keep = pos < capacity
    return jnp.where(keep, idx, -1).reshape(_unwrap(topk_idx).shape), pos.reshape(
        _unwrap(topk_idx).shape
    )


def gshard_dispatch(gate_val, gate_idx, num_expert: int, capacity: int):
    """Build dispatch one-hot [T, E, C] and combine weights [T, E, C] from
    top-k gate outputs ([T, k] each). Overflow tokens are dropped."""
    val = _unwrap(gate_val)
    idx = _unwrap(gate_idx)
    T, k = idx.shape
    dispatch = jnp.zeros((T, num_expert, capacity), val.dtype)
    combine = jnp.zeros((T, num_expert, capacity), val.dtype)
    # positions computed per k-choice in priority order (choice 0 first),
    # matching the reference's sequential count_by_gate over topk columns
    running = jnp.zeros((num_expert,), jnp.int32)
    for j in range(k):
        e = idx[:, j]
        one = jax.nn.one_hot(e, num_expert, dtype=jnp.int32)  # [T, E]
        pos = running[None, :] + jnp.cumsum(one, axis=0) - 1  # [T, E]
        slot = jnp.sum(pos * one, axis=-1)                    # [T]
        keep = slot < capacity
        slot_c = jnp.clip(slot, 0, capacity - 1)
        oh = (jax.nn.one_hot(e, num_expert, dtype=val.dtype)[..., None]
              * jax.nn.one_hot(slot_c, capacity, dtype=val.dtype)[:, None, :])
        oh = jnp.where(keep[:, None, None], oh, 0.0)
        dispatch = dispatch + oh
        combine = combine + oh * val[:, j][:, None, None]
        running = running + jnp.sum(one, axis=0)
    return dispatch, combine


class MoELayer(nn.Layer):
    """Expert-parallel mixture-of-experts layer (reference: MoELayer in
    moe_layer.py; call signature kept: experts list + gate config/dict).

    ``experts``: list of structurally-identical nn.Layers (the global expert
    set — the reference holds ``num_expert`` local experts per rank; here the
    stacked global set is sharded over ``axis_name`` when a mesh is active).
    """

    def __init__(self, d_model: int, experts: Sequence[nn.Layer],
                 gate=None, moe_group=None, mp_group=None,
                 recompute_interval: int = 0, capacity_factor=None,
                 axis_name: str = "dp", use_ragged: Optional[bool] = None,
                 dropless: bool = False, **kwargs):
        super().__init__()
        self.d_model = d_model
        self.experts = nn.LayerList(list(experts))
        self.num_expert = len(self.experts)
        # None → use the gate's (train, eval) capacity factors
        self.capacity_factor = (None if capacity_factor is None
                                else float(capacity_factor))
        self.axis_name = axis_name
        # ragged grouped-GEMM expert compute (VERDICT r1 #6): None = auto
        # (on when every expert is an ExpertFFN with one activation and no
        # EP sharding is active), True = require, False = force dense.
        self.use_ragged = use_ragged
        # dropless (megablocks) routing: no capacity drop — ragged path only
        self.dropless = bool(dropless)
        if self.dropless and use_ragged is False:
            raise ValueError("dropless routing requires the ragged path")
        # padding fraction of the dense path this layer last avoided (set on
        # each ragged forward; static — depends only on shapes/capacity)
        self.last_padded_fraction: Optional[float] = None
        if gate is None:
            gate = GShardGate(d_model, self.num_expert)
        elif isinstance(gate, dict):
            gtype = gate.get("type", "gshard")
            topk = gate.get("top_k", 2)
            cls = {"gshard": GShardGate, "switch": SwitchGate,
                   "naive": NaiveGate}[gtype]
            gate = cls(d_model, self.num_expert, topk=topk)
        if not isinstance(gate, BaseGate):
            raise TypeError(f"gate must be a BaseGate, got {type(gate)}")
        self.gate = gate
        # structural identity check for stacking
        sig = [tuple((n, tuple(p.shape)) for n, p in e.named_parameters())
               for e in self.experts]
        if any(s != sig[0] for s in sig):
            raise ValueError("MoELayer experts must be structurally identical")

    # ------------------------------------------------------------------
    def _stacked_expert_params(self):
        leaves = [n for n, _ in self.experts[0].named_parameters()]
        per = [dict(e.named_parameters()) for e in self.experts]
        return {
            leaf: jnp.stack([_unwrap(p[leaf]) for p in per]) for leaf in leaves
        }

    def _expert_sharding(self):
        """NamedSharding for [E, C, H] expert tensors when a hybrid mesh with
        the expert axis is active (GSPMD inserts the a2a), else None."""
        try:
            from .....distributed.parallel import get_mesh

            mesh = get_mesh()
        except Exception:
            return None
        if (mesh is None or self.axis_name not in mesh.axis_names
                or mesh.shape[self.axis_name] == 1
                or self.num_expert % mesh.shape[self.axis_name]):
            return None
        return jax.sharding.NamedSharding(mesh, P(self.axis_name, None, None))

    def _ragged_active(self) -> bool:
        """Ragged grouped-GEMM path applies when experts are canonical FFNs
        (one shared activation) and no EP sharding is active — inside an
        ep-sharded mesh the all-to-all needs the static [E, C, H] layout."""
        if self.use_ragged is False:
            return False
        eligible = (
            all(isinstance(e, ExpertFFN) for e in self.experts)
            and len({e.activation for e in self.experts}) == 1
            and self._expert_sharding() is None
        )
        if (self.use_ragged or self.dropless) and not eligible:
            raise ValueError(
                "use_ragged=True/dropless=True need ExpertFFN experts with "
                "one shared activation and no expert-parallel sharding "
                "(the dense EP path drops tokens at capacity)"
            )
        return eligible

    def _ragged_forward(self, xt, val, idx, capacity: int):
        stacked = self._stacked_expert_params()
        act = _ACT_FNS[self.experts[0].activation]
        cap = None if self.dropless else capacity
        T = xt.shape[0]
        self.last_padded_fraction = padded_flops_fraction(
            self.num_expert, capacity, T, self.gate.top_k
        )
        return moe_ragged_ffn(
            xt, idx, val,
            stacked["fc1.weight"], stacked["fc1.bias"],
            stacked["fc2.weight"], stacked["fc2.bias"],
            act, cap,
        )

    def _capacity(self, T: int) -> int:
        factor = self.capacity_factor
        if factor is None:
            cap = getattr(self.gate, "capacity", (1.2, 2.4))
            factor = cap[0] if self.training else cap[1]
        return max(1, int(float(factor) * self.gate.top_k * T
                          / self.num_expert))

    def _pure_forward(self, x):
        """Routing + expert compute on raw arrays (params read through the
        layer tree — tracers when swapped by functional_call/apply_op).
        Returns (y, aux_loss_or_None)."""
        orig_shape = x.shape
        H = orig_shape[-1]
        xt = x.reshape(-1, H)  # [T, H]
        T = xt.shape[0]

        gate_out = self.gate(Tensor._wrap(xt))
        val, idx = gate_out[0], gate_out[1]
        capacity = self._capacity(T)

        if self._ragged_active():
            y = self._ragged_forward(xt, _unwrap(val), _unwrap(idx), capacity)
            aux = self.gate.get_loss()
            return y.reshape(orig_shape), (
                aux._data if isinstance(aux, Tensor) else aux
            )

        dispatch, combine = gshard_dispatch(val, idx, self.num_expert,
                                            capacity)
        expert_in = jnp.einsum("tec,th->ech", dispatch, xt)

        sharding = self._expert_sharding()
        if sharding is not None:
            expert_in = jax.lax.with_sharding_constraint(expert_in, sharding)

        template = self.experts[0]
        stacked = self._stacked_expert_params()

        from .....jit import functional_call

        def apply_one(leaf_params, tokens):
            with pause_tape():
                return functional_call(template, leaf_params,
                                       Tensor._wrap(tokens))

        expert_out = jax.vmap(apply_one)(stacked, expert_in)  # [E, C, H]
        if sharding is not None:
            expert_out = jax.lax.with_sharding_constraint(expert_out, sharding)
        y = jnp.einsum("tec,ech->th", combine, expert_out)
        aux = self.gate.get_loss()
        return y.reshape(orig_shape), (
            aux._data if isinstance(aux, Tensor) else aux
        )

    def forward(self, inp):
        """Eager-autograd-correct forward: the whole routed computation is one
        tape node (apply_op) whose primals are the input plus every gate and
        expert parameter, so ``loss.backward()`` reaches them (repo
        convention; see incubate/nn/layer/fused_transformer.py)."""
        named = list(self.named_parameters())
        has_aux = not isinstance(self.gate, NaiveGate)

        def fn(x, *arrs):
            saved = [p._data for _, p in named]
            try:
                for (_, p), a in zip(named, arrs):
                    p._data = a
                with pause_tape():
                    y, aux = self._pure_forward(x)
                if has_aux:
                    return y, (aux if aux is not None
                               else jnp.zeros((), y.dtype))
                return y
            finally:
                for (_, p), d in zip(named, saved):
                    p._data = d

        out = apply_op(fn, inp, *[p for _, p in named])
        if has_aux:
            y, aux = out
            self.gate.set_loss(aux)
            return y
        return out
