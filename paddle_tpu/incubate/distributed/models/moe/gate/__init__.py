"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/
— naive_gate.py, gshard_gate.py, switch_gate.py).

Each gate maps token activations [T, d_model] to routing decisions. The
GShard/Switch gates carry a load-balancing auxiliary loss retrievable via
``get_loss()`` (reference semantics: ``gate.loss`` accumulated per forward).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...... import nn
from ......framework.tensor import Tensor, apply_op

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


class BaseGate(nn.Layer):
    def __init__(self, num_expert: int, world_size: int = 1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self._loss = None

    def set_loss(self, loss):
        self._loss = loss

    def get_loss(self, clear: bool = True):
        loss = self._loss
        if clear:
            self._loss = None
        return loss

    @property
    def has_loss(self) -> bool:
        return self._loss is not None


class NaiveGate(BaseGate):
    """Linear gate, top-k routing with softmax-over-selected combine weights,
    no auxiliary loss (reference: gate/naive_gate.py — FastMoE-style
    ``gate_score = softmax(topk_vals)``)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2):
        super().__init__(num_expert, world_size)
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores: bool = False):
        gate_logits = self.gate(inp)  # taped Linear keeps eager AD alive
        k = self.top_k
        idx = Tensor._wrap(jax.lax.top_k(_unwrap(gate_logits), k)[1])
        # differentiable value path recorded as ONE tape node
        val = apply_op(
            lambda g: jax.nn.softmax(jax.lax.top_k(g, k)[0], axis=-1),
            gate_logits,
        )
        if return_all_scores:
            return (val, idx, gate_logits)
        return val, idx


def _load_balance_loss(gates, mask_first):
    """GShard/Switch aux loss: E · Σ_e density_e · density_proxy_e."""
    E = gates.shape[-1]
    density = jnp.mean(mask_first, axis=0)        # fraction routed (top-1)
    density_proxy = jnp.mean(gates, axis=0)       # mean gate prob
    return jnp.sum(density * density_proxy) * E


class GShardGate(BaseGate):
    """Top-2 gate with load-balance aux loss and optional capacity
    (reference: gate/gshard_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2, capacity=(1.2, 2.4), random_routing: bool = True,
                 group=None):
        super().__init__(num_expert, world_size)
        if topk != 2:
            raise ValueError("GShardGate reference implementation uses topk=2")
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.top_k = 2
        self.capacity = capacity
        self.random_routing = random_routing

    def forward(self, inp):
        logits_t = self.gate(inp)
        logits = _unwrap(logits_t)
        gates = jax.nn.softmax(logits, axis=-1)
        val, idx = jax.lax.top_k(gates, 2)
        top1 = idx[..., 0]
        # aux loss as a tape node of the logits → standalone backward works
        self.set_loss(apply_op(
            lambda g: _load_balance_loss(
                jax.nn.softmax(g, axis=-1),
                jax.nn.one_hot(top1, self.tot_expert)),
            logits_t,
        ))
        val = apply_op(
            lambda g: jax.lax.top_k(jax.nn.softmax(g, axis=-1), 2)[0],
            logits_t,
        )
        if self.random_routing and self.training:
            # reference _random_routing (moe/utils.py): drop the 2nd expert
            # when its gate prob is small relative to a uniform draw —
            # one_hot(-1) dispatches nothing downstream
            from ......framework import random as _random

            val_arr = _unwrap(val)
            r = jax.random.uniform(_random.op_key(), (idx.shape[0],),
                                   val_arr.dtype)
            second = jnp.where(2.0 * val_arr[..., 1] < r, -1, idx[..., 1])
            idx = jnp.stack([idx[..., 0], second], axis=-1)
        return val, Tensor._wrap(idx)


class SwitchGate(BaseGate):
    """Top-1 gate (Switch Transformer) with aux loss (reference:
    gate/switch_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 1, switch_eps: float = 0.1, capacity=(1.2, 2.4),
                 group=None):
        super().__init__(num_expert, world_size)
        if topk != 1:
            raise ValueError("SwitchGate routes top-1")
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.top_k = 1
        self.switch_eps = switch_eps
        self.capacity = capacity

    def forward(self, inp):
        logits_t = self.gate(inp)
        noise = None
        if self.training and self.switch_eps > 0:
            from ......framework import random as _random

            noise = jax.random.uniform(
                _random.op_key(), _unwrap(logits_t).shape,
                _unwrap(logits_t).dtype,
                1.0 - self.switch_eps, 1.0 + self.switch_eps,
            )

        def gated(g):
            if noise is not None:
                g = g * noise
            return jax.nn.softmax(g, axis=-1)

        idx = jax.lax.top_k(gated(_unwrap(logits_t)), 1)[1]
        top1 = idx[..., 0]
        self.set_loss(apply_op(
            lambda g: _load_balance_loss(
                gated(g), jax.nn.one_hot(top1, self.tot_expert)),
            logits_t,
        ))
        val = apply_op(lambda g: jax.lax.top_k(gated(g), 1)[0], logits_t)
        return val, Tensor._wrap(idx)
