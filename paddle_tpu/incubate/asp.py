"""Automatic SParsity — 2:4 structured sparsity (reference:
python/paddle/incubate/asp/ + fleet/meta_optimizers/asp_optimizer.py:
prune weights to n-of-m pattern, then keep the mask fixed through training
by masking weights after every optimizer step).

TPU note: the reference's payoff is Ampere sparse-tensor-core GEMMs; XLA has
no 2:4 MXU path, so here ASP is a MODEL-QUALITY feature (train a sparse
network, export it) with the same API. Masks live per-parameter; the
decorated optimizer re-applies them after each step so pruned weights stay
exactly zero.
"""
from __future__ import annotations

import weakref
from typing import Dict, Optional

import jax.numpy as jnp

__all__ = ["calculate_density", "compute_mask_2to4", "prune_model",
           "decorate", "ASPOptimizer"]

# id(param) -> (weakref(param), mask). The weakref is VALIDATED on lookup:
# CPython recycles ids, so a bare id-keyed dict could hand a dead
# parameter's mask to an unrelated new object.
_MASKS: Dict[int, tuple] = {}


def _mask_for(p):
    entry = _MASKS.get(id(p))
    if entry is None:
        return None
    ref, mask = entry
    if ref() is not p:  # stale id from a collected parameter
        del _MASKS[id(p)]
        return None
    return mask


def _register_mask(p, mask):
    key = id(p)

    def _purge(_ref, _key=key):
        _MASKS.pop(_key, None)  # free the mask when the parameter dies

    _MASKS[key] = (weakref.ref(p, _purge), mask)


def compute_mask_2to4(w, n: int = 2, m: int = 4, axis: int = -1):
    """Keep the ``n`` largest-magnitude entries of every group of ``m``
    along ``axis``. The 1-D n:m pattern must run along the GEMM reduction
    dim to be consumable by sparse-tensor-core GEMMs; for this framework's
    ``[in_features, out_features]`` Linear weights that is axis 0 (what
    ``prune_model`` passes)."""
    w = jnp.moveaxis(w, axis, -1)
    if w.shape[-1] % m:
        mask = jnp.ones_like(w, dtype=bool)
    else:
        g = w.reshape(w.shape[:-1] + (w.shape[-1] // m, m))
        order = jnp.argsort(jnp.abs(g), axis=-1)  # ascending
        ranks = jnp.argsort(order, axis=-1)  # rank of each entry per group
        mask = (ranks >= (m - n)).reshape(w.shape)
    return jnp.moveaxis(mask, -1, axis)


def calculate_density(x) -> float:
    import numpy as np

    a = np.asarray(getattr(x, "_data", x))
    return float((a != 0).sum() / a.size)


def _prunable_weights(model):
    """GEMM weights only — Linear layers' 2-D kernels (reference ASP prunes
    FC/Conv, never embeddings: an n:m pattern across unrelated vocabulary
    rows destroys quality with no sparse-GEMM payoff)."""
    from .. import nn

    for layer_name, layer in [("", model)] + list(model.named_sublayers()):
        if isinstance(layer, nn.Linear):
            prefix = f"{layer_name}." if layer_name else ""
            yield f"{prefix}weight", layer.weight


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Prune every Linear weight of ``model`` to the n:m pattern and
    register its mask (reference: paddle.incubate.asp.prune_model)."""
    import warnings

    masks = {}
    for name, p in _prunable_weights(model):
        if p.shape[0] % m:
            warnings.warn(
                f"asp.prune_model: {name} has in_features={p.shape[0]} not "
                f"divisible by {m} — left dense (no mask registered)",
                RuntimeWarning, stacklevel=2)
            continue
        # axis 0 = in_features = the y = xW reduction dim
        mask = compute_mask_2to4(p._data, n=n, m=m, axis=0)
        p._data = jnp.where(mask, p._data, 0)
        if with_mask:
            _register_mask(p, mask)
            masks[name] = mask
    return masks


class ASPOptimizer:
    """Masked optimizer wrapper: after each inner step, re-zero pruned
    entries so the sparsity pattern survives updates (reference:
    asp_optimizer.py OptimizerWithSparsityGuarantee)."""

    def __init__(self, optimizer, model=None):
        self._inner_opt = optimizer
        # optional scope: only this model's parameters get re-masked
        self._scope_ids = (None if model is None else
                           {id(p) for _, p in model.named_parameters()})

    def step(self):
        self._inner_opt.step()
        for p in self._inner_opt._parameter_list():
            if self._scope_ids is not None and id(p) not in self._scope_ids:
                continue
            mask = _mask_for(p)
            if mask is not None:
                p._data = jnp.where(mask, p._data, 0)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)


def decorate(optimizer, model: Optional[object] = None) -> ASPOptimizer:
    """paddle.incubate.asp.decorate parity."""
    return ASPOptimizer(optimizer, model)
