"""paddle.incubate.nn parity (reference: python/paddle/incubate/nn/)."""
from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedFeedForward,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)

__all__ = [
    "functional",
    "FusedFeedForward",
    "FusedMultiHeadAttention",
    "FusedMultiTransformer",
    "FusedTransformerEncoderLayer",
]
