"""incubate.nn.functional (reference: python/paddle/incubate/nn/functional/
— fused_multi_transformer, fused_feedforward, fused_multi_head_attention,
masked_multihead_attention)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....framework.tensor import Tensor, apply_op

__all__ = [
    "fused_feedforward",
    "fused_multi_head_attention",
    "masked_multihead_attention",
]


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5, pre_layer_norm=False,
                      training=True, mode="upscale_in_train", name=None):
    """Functional twin of FusedFeedForward (reference:
    incubate/nn/functional/fused_transformer.py fused_feedforward)."""
    from ....nn import functional as F

    residual = x
    d = x.shape[-1]
    if pre_layer_norm:
        x = F.layer_norm(x, [d], ln1_scale, ln1_bias, ln1_epsilon)
    act = {"gelu": lambda a: F.gelu(a, approximate=True), "relu": F.relu}[activation]
    h = act(x.matmul(linear1_weight) + (linear1_bias if linear1_bias is not None else 0))
    h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = h.matmul(linear2_weight) + (linear2_bias if linear2_bias is not None else 0)
    h = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = residual + h
    if not pre_layer_norm:
        out = F.layer_norm(out, [d], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, name=None):
    """Functional twin of FusedMultiHeadAttention. qkv_weight layout
    [3, nh, hd, H] (trans_qkvw)."""
    from ....nn import functional as F
    from ..layer.fused_transformer import _qkv_pack

    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention: cache_kv (incremental decode) is not "
            "supported here — use masked_multihead_attention or "
            "FusedMultiTransformer's cache path; silently dropping it would "
            "compute non-cached attention and a stale cache")
    residual = x
    d = x.shape[-1]
    if pre_layer_norm:
        x = F.layer_norm(x, [d], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    b, s, _ = x.shape
    qkv = _qkv_pack(x, qkv_weight, qkv_bias)
    q, k, v = qkv.unbind(axis=2)
    if attn_mask is not None:
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             dropout_p=attn_dropout_rate,
                                             training=training)
    else:
        out, _ = F.flash_attention(q, k, v, dropout=attn_dropout_rate,
                                   causal=False, training=training)
    out = out.reshape([b, s, d]).matmul(linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [d], ln_scale, ln_bias, ln_epsilon)
    return out


def masked_multihead_attention(x, cache_kv=None, src_mask=None, cum_offsets=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """Single-token decode attention against a KV cache (reference:
    paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu).

    ``x`` is the current token's packed qkv [B, 3*H]; ``cache_kv`` is
    [2, B, nh, S, hd]; ``sequence_lengths`` [B] gives each element's current
    length (the new token is written at that index). Returns
    (out [B, H], updated cache_kv) — functional cache update.
    """
    from ....ops.pallas.decode_attention import decode_attention

    unsupported = {
        "src_mask": src_mask, "cum_offsets": cum_offsets,
        "rotary_tensor": rotary_tensor, "beam_cache_offset": beam_cache_offset,
        "qkv_out_scale": qkv_out_scale, "out_shift": out_shift,
        "out_smooth": out_smooth,
    }
    bad = [k for k, v in unsupported.items() if v is not None]
    if rotary_emb_dims:
        bad.append("rotary_emb_dims")
    if out_scale != -1:
        bad.append("out_scale")
    if bad:
        raise NotImplementedError(
            f"masked_multihead_attention: unsupported arguments {bad} "
            "(rotary/quant variants are not implemented — silently dropping "
            "them would compute wrong attention)")

    xv = _unwrap(x)
    cv = _unwrap(cache_kv)
    _, bsz, nh, smax, hd = cv.shape
    qkv = xv.reshape(bsz, 3, nh, hd)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [b,nh,hd]
    if sequence_lengths is None:
        raise ValueError("masked_multihead_attention requires sequence_lengths")
    lens = _unwrap(sequence_lengths).astype(jnp.int32).reshape(-1)

    # per-batch sliced write of the new token at position lens[b]
    upd = jnp.stack([k, v]).astype(cv.dtype)  # [2,b,nh,hd]
    cv = jax.vmap(
        lambda c, u, l: jax.lax.dynamic_update_slice(c, u[:, :, None], (0, 0, l, 0)),
        in_axes=(1, 1, 0), out_axes=1,
    )(cv, upd, lens)
    out = decode_attention(q, cv[0], cv[1], lens + 1)
    out = out.reshape(bsz, nh * hd)
    return Tensor._wrap(out), Tensor._wrap(cv)


def ring_flash_attention(q, k, v, causal=True, axis_name="sep", **kw):
    """PaddleNLP-parity alias (reference ecosystem: ring_flash_attention.py)
    over the native context-parallel ring kernel. Records a tape node so the
    eager/dygraph backward reaches q/k/v."""
    from ....distributed.fleet.meta_parallel.context_parallel import (
        ring_attention_op,
    )

    return ring_attention_op(q, k, v, causal=causal, axis_name=axis_name,
                             **kw)


__all__.append("ring_flash_attention")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """Rotary embedding applied to q/k[/v] in one pass (reference:
    paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu via
    incubate.nn.functional.fused_rotary_position_embedding; SURVEY A3.x —
    "fold into attention"). Layout [batch, seq, heads, head_dim].

    When sin/cos are None they are computed from ``rotary_emb_base``
    (optionally gathered at ``position_ids``). Returns (q, k, v) with None
    passed through.
    """
    qa = _unwrap(q)
    if time_major:  # [seq, batch, h, d] — normalize to batch-major
        s, b = qa.shape[0], qa.shape[1]
    else:
        b, s = qa.shape[0], qa.shape[1]
    d = qa.shape[-1]

    def expand(tab):  # [*, d] table → broadcastable over [b, s, h, d]
        if tab.ndim == 3:  # per-batch positions [b, s, d]
            out = tab[:, :, None, :]
        else:
            out = tab[None, :, None, :]
        return jnp.swapaxes(out, 0, 1) if time_major else out

    if sin is None or cos is None:
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, d, 2,
                                                    dtype=jnp.float32) / d))
        if position_ids is not None:
            # compute freqs AT the requested positions (decode steps pass
            # positions ≥ current seq length — a gathered arange(s) table
            # would clamp them)
            pos = _unwrap(position_ids).astype(jnp.float32)  # [b, s]
            freqs = pos[..., None] * inv  # [b, s, d/2]
        else:
            freqs = jnp.outer(jnp.arange(s, dtype=jnp.float32), inv)
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], axis=-1)
        else:
            emb = jnp.repeat(freqs, 2, axis=-1)
        sin_a, cos_a = expand(jnp.sin(emb)), expand(jnp.cos(emb))
    else:
        sin_t = _unwrap(sin).reshape(-1, d)
        cos_t = _unwrap(cos).reshape(-1, d)
        if position_ids is not None:
            pos = _unwrap(position_ids)
            sin_a, cos_a = expand(sin_t[pos]), expand(cos_t[pos])
        else:
            sin_a, cos_a = expand(sin_t[:s]), expand(cos_t[:s])

    def rotate(x):
        if use_neox_rotary_style:
            x1, x2 = x[..., : d // 2], x[..., d // 2:]
            return jnp.concatenate([-x2, x1], axis=-1)
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)

    def ap(x):
        c = cos_a.astype(x.dtype)
        si = sin_a.astype(x.dtype)
        return x * c + rotate(x) * si

    outs = []
    for t_in in (q, k, v):
        if t_in is None:
            outs.append(None)
        else:
            outs.append(apply_op(ap, t_in))
    return tuple(outs)


__all__.append("fused_rotary_position_embedding")


def fused_softmax_mask(x, mask, scale=1.0):
    """softmax(scale·x + mask) fused (reference:
    paddle/fluid/operators/fused/fused_softmax_mask_op.cu). On TPU this is
    one XLA fusion; kept for API parity — inside attention it lives in the
    flash kernel."""
    m = _unwrap(mask)
    return apply_op(
        lambda a: jax.nn.softmax(a.astype(jnp.float32) * scale + m,
                                 axis=-1).astype(a.dtype), x)


def fused_softmax_mask_upper_triangle(x):
    """Causal softmax (reference: fused_softmax_mask_upper_triangle_op.cu):
    softmax over the last dim with the strict upper triangle masked."""
    def fn(a):
        sq, sk = a.shape[-2], a.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(causal, a.astype(jnp.float32), -jnp.inf)
        return jax.nn.softmax(s, axis=-1).astype(a.dtype)

    return apply_op(fn, x)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      seed=None, name=None):
    """dropout(x) + y in one pass (reference:
    paddle/phi/kernels/fusion/gpu/fused_dropout_add_kernel.cu — the saved
    seed/offset for exact backward replay is the PRNG key here, which the
    trace replays bit-exactly by construction)."""
    from ....nn import functional as F

    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_linear_activation(x, weight, bias=None, trans_x=False,
                            trans_y=False, activation="gelu"):
    """GEMM + bias + activation epilogue (reference: fused_gemm_epilogue_op
    via cublasLt; XLA fuses the epilogue into the matmul on TPU)."""
    from ....nn import functional as F

    xa = x if not trans_x else x.transpose(
        list(range(x.ndim - 2)) + [x.ndim - 1, x.ndim - 2])
    wa = weight if not trans_y else weight.transpose(
        list(range(weight.ndim - 2)) + [weight.ndim - 1, weight.ndim - 2])
    out = xa.matmul(wa)
    if bias is not None:
        out = out + bias
    acts = {"gelu": lambda a: F.gelu(a, approximate=True), "relu": F.relu,
            "none": lambda a: a, None: lambda a: a}
    if activation not in acts:
        raise ValueError(
            f"fused_linear_activation: unsupported activation "
            f"{activation!r}; choose from {sorted(k for k in acts if k)}")
    return acts[activation](out)


fused_gemm_epilogue = fused_linear_activation  # reference op name


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True,
                                           mode="upscale_in_train",
                                           name=None):
    """(x + bias) → dropout → + residual → LayerNorm (reference:
    fused_bias_dropout_residual_layer_norm_kernel.cu)."""
    from ....nn import functional as F

    h = x if bias is None else x + bias
    h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
    h = h + residual
    d = h.shape[-1]
    return F.layer_norm(h, [d], ln_scale, ln_bias, ln_epsilon)


__all__ += ["fused_softmax_mask", "fused_softmax_mask_upper_triangle",
            "fused_dropout_add", "fused_linear_activation",
            "fused_gemm_epilogue", "fused_bias_dropout_residual_layer_norm"]
