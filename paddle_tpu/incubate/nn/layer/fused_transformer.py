"""Fused transformer layers (reference: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention, FusedFeedForward,
FusedMultiTransformer, FusedTransformerEncoderLayer; backed by
paddle/fluid/operators/fused/fused_multi_transformer_op.cu and
fused_attention_op.cu / fused_feedforward_op.cu).

TPU-native translation (SURVEY.md A3.x plan): the per-layer dataflow is the
same — pre-LN → packed QKV GEMM → attention → out-proj (+mp allreduce) →
residual+LN → FFN1 → act → FFN2 (+mp allreduce) → residual — but the GEMMs
stay XLA (MXU), attention routes to the Pallas flash kernel (context phase)
or the Pallas decode kernel with KV cache (generation phase), and the
`ring_id` mp-allreduce hook becomes a sharding spec: weights carry 'mp'
PartitionSpecs so GSPMD inserts the collectives the CUDA kernel hand-rolls.

Weight-layout parity for checkpoint import: qkv weight is stored
[3, num_heads, head_dim, embed_dim] (trans_qkvw=True layout), qkv bias
[3, num_heads, head_dim], caches [2, bsz, num_heads, max_seq, head_dim] —
exactly the reference's shapes.
"""
from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .... import nn
from ....framework.tensor import Tensor, apply_op
from ....nn import functional as F

__all__ = [
    "FusedMultiHeadAttention",
    "FusedFeedForward",
    "FusedMultiTransformer",
    "FusedTransformerEncoderLayer",
]


def _act(name):
    return {"gelu": lambda x: F.gelu(x, approximate=True), "relu": F.relu}[name]


class FusedMultiHeadAttention(nn.Layer):
    """Pre/post-LN + packed QKV + attention + out-proj + dropout/residual in
    one composite (reference: fused_attention_op.cu). XLA fuses the
    elementwise epilogues; attention is the Pallas flash kernel."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon

        self.qkv_weight = self.create_parameter(
            shape=[3, num_heads, self.head_dim, embed_dim], attr=qkv_weight_attr)
        self.qkv_weight.is_distributed = True
        self.qkv_weight.dist_spec = P(None, "mp", None, None)
        self.qkv_bias = self.create_parameter(
            shape=[3, num_heads, self.head_dim], attr=qkv_bias_attr, is_bias=True)
        self.qkv_bias.is_distributed = True
        self.qkv_bias.dist_spec = P(None, "mp", None)
        self.linear_weight = self.create_parameter(
            shape=[embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_weight.is_distributed = True
        self.linear_weight.dist_spec = P("mp", None)
        self.linear_bias = self.create_parameter(
            shape=[embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            shape=[embed_dim], attr=pre_ln_scale_attr,
            default_initializer=nn.initializer.Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            shape=[embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            shape=[embed_dim], attr=ln_scale_attr,
            default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter(shape=[embed_dim], attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedMultiHeadAttention: cache (incremental decode) is not "
                "supported — use FusedMultiTransformer's caches/time_step path; "
                "silently dropping it would compute non-cached attention")
        x = query
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], self.pre_ln_scale,
                             self.pre_ln_bias, self.epsilon)
        b, s, _ = x.shape
        qkv = _qkv_pack(x, self.qkv_weight, self.qkv_bias)  # [b,s,3,nh,hd]
        q, k, v = qkv.unbind(axis=2)
        if attn_mask is not None:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
                training=self.training)
        else:
            out, _ = F.flash_attention(q, k, v, dropout=self.attn_dropout_rate,
                                       causal=False, training=self.training)
        out = out.reshape([b, s, self.embed_dim]).matmul(self.linear_weight)
        out = out + self.linear_bias
        out = F.dropout(out, p=self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = F.layer_norm(out, [self.embed_dim], self.ln_scale, self.ln_bias,
                               self.epsilon)
        return out


class FusedFeedForward(nn.Layer):
    """LN + linear1 + act + dropout + linear2 + dropout + residual
    (reference: fused_feedforward_op.cu — XLA fuses this chain natively)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None,
                 ln2_scale_attr=None, ln2_bias_attr=None, nranks=1, ring_id=-1,
                 name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = dropout_rate if act_dropout_rate is None else act_dropout_rate
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            shape=[d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_weight.is_distributed = True
        self.linear1_weight.dist_spec = P(None, "mp")
        self.linear1_bias = self.create_parameter(
            shape=[dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear1_bias.is_distributed = True
        self.linear1_bias.dist_spec = P("mp")
        self.linear2_weight = self.create_parameter(
            shape=[dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_weight.is_distributed = True
        self.linear2_weight.dist_spec = P("mp", None)
        self.linear2_bias = self.create_parameter(
            shape=[d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter(
            shape=[d_model], attr=ln1_scale_attr,
            default_initializer=nn.initializer.Constant(1.0))
        self.ln1_bias = self.create_parameter(shape=[d_model], attr=ln1_bias_attr,
                                              is_bias=True)
        self.ln2_scale = self.create_parameter(
            shape=[d_model], attr=ln2_scale_attr,
            default_initializer=nn.initializer.Constant(1.0))
        self.ln2_bias = self.create_parameter(shape=[d_model], attr=ln2_bias_attr,
                                              is_bias=True)

    def forward(self, src):
        residual = src
        x = src
        if self.normalize_before:
            x = F.layer_norm(x, [self.d_model], self.ln1_scale, self.ln1_bias,
                             self.epsilon)
        x = _act(self.activation)(x.matmul(self.linear1_weight) + self.linear1_bias)
        x = F.dropout(x, p=self.act_dropout_rate, training=self.training)
        x = x.matmul(self.linear2_weight) + self.linear2_bias
        x = F.dropout(x, p=self.dropout_rate, training=self.training)
        x = residual + x
        if not self.normalize_before:
            x = F.layer_norm(x, [self.d_model], self.ln2_scale, self.ln2_bias,
                             self.epsilon)
        return x


def _qkv_pack(x, qkv_weight, qkv_bias):
    """[b,s,H] × [3,nh,hd,H] (+[3,nh,hd]) → [b,s,3,nh,hd] — the packed-QKV
    GEMM with the reference's trans_qkvw weight layout."""

    def fn(xa, wa, ba):
        out = jnp.einsum("bsh,tndh->bstnd", xa, wa.astype(xa.dtype))
        if ba is not None:
            out = out + ba.astype(xa.dtype)
        return out

    if qkv_bias is None:
        return apply_op(lambda xa, wa: fn(xa, wa, None), x, qkv_weight)
    return apply_op(fn, x, qkv_weight, qkv_bias)


class FusedMultiTransformer(nn.Layer):
    """Whole decoder stack as one layer (reference:
    fused_multi_transformer_op.cu — "one call = ALL layers' params as tensor
    lists", SURVEY.md §3.5). Pre-LN only, like the reference.

    forward(src, caches=..., time_step=...) implements both phases:
      * context (time_step None): causal flash attention over the full
        prompt; writes k/v into the caches' first seq positions;
      * decode (time_step int): one token per call, appends to cache at
        time_step, attends via the Pallas decode kernel.
    Caches are [2, bsz, num_heads, max_seq, head_dim] per layer and are
    returned updated (functional update — in-place mutation is not a TPU
    concept; callers thread them, reference semantics preserved).
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None, epsilon=1e-5,
                 num_layers=-1, nranks=1, trans_qkvw=True, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        assert normalize_before, "reference kernel is pre-LN only"
        assert trans_qkvw, "only the [3,nh,hd,H] qkv layout is supported"
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dim_feedforward = dim_feedforward
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.epsilon = epsilon
        if num_layers == -1:
            num_layers = len(qkv_weight_attrs) if isinstance(qkv_weight_attrs, (list, tuple)) else 1
        self.num_layers = num_layers

        def attr_at(attrs, i):
            return attrs[i] if isinstance(attrs, (list, tuple)) else attrs

        self.ln_scales, self.ln_biases = [], []
        self.qkv_weights, self.qkv_biases = [], []
        self.linear_weights, self.linear_biases = [], []
        self.ffn_ln_scales, self.ffn_ln_biases = [], []
        self.ffn1_weights, self.ffn1_biases = [], []
        self.ffn2_weights, self.ffn2_biases = [], []
        h, nh, hd, ff = embed_dim, num_heads, self.head_dim, dim_feedforward
        for i in range(num_layers):
            ln_s = self.create_parameter([h], attr_at(ln_scale_attrs, i),
                                         default_initializer=nn.initializer.Constant(1.0))
            ln_b = self.create_parameter([h], attr_at(ln_bias_attrs, i), is_bias=True)
            qkv_w = self.create_parameter([3, nh, hd, h], attr_at(qkv_weight_attrs, i),
                                          default_initializer=nn.initializer.XavierNormal())
            qkv_w.is_distributed = True
            qkv_w.dist_spec = P(None, "mp", None, None)
            qkv_b = self.create_parameter([3, nh, hd], attr_at(qkv_bias_attrs, i),
                                          is_bias=True)
            qkv_b.is_distributed = True
            qkv_b.dist_spec = P(None, "mp", None)
            lin_w = self.create_parameter([h, h], attr_at(linear_weight_attrs, i),
                                          default_initializer=nn.initializer.XavierNormal())
            lin_w.is_distributed = True
            lin_w.dist_spec = P("mp", None)
            lin_b = self.create_parameter([h], attr_at(linear_bias_attrs, i), is_bias=True)
            fln_s = self.create_parameter([h], attr_at(ffn_ln_scale_attrs, i),
                                          default_initializer=nn.initializer.Constant(1.0))
            fln_b = self.create_parameter([h], attr_at(ffn_ln_bias_attrs, i), is_bias=True)
            f1_w = self.create_parameter([h, ff], attr_at(ffn1_weight_attrs, i),
                                         default_initializer=nn.initializer.XavierNormal())
            f1_w.is_distributed = True
            f1_w.dist_spec = P(None, "mp")
            f1_b = self.create_parameter([ff], attr_at(ffn1_bias_attrs, i), is_bias=True)
            f1_b.is_distributed = True
            f1_b.dist_spec = P("mp")
            f2_w = self.create_parameter([ff, h], attr_at(ffn2_weight_attrs, i),
                                         default_initializer=nn.initializer.XavierNormal())
            f2_w.is_distributed = True
            f2_w.dist_spec = P("mp", None)
            f2_b = self.create_parameter([h], attr_at(ffn2_bias_attrs, i), is_bias=True)

            for name_, p in (
                (f"ln_scales.{i}", ln_s), (f"ln_biases.{i}", ln_b),
                (f"qkv_weights.{i}", qkv_w), (f"qkv_biases.{i}", qkv_b),
                (f"linear_weights.{i}", lin_w), (f"linear_biases.{i}", lin_b),
                (f"ffn_ln_scales.{i}", fln_s), (f"ffn_ln_biases.{i}", fln_b),
                (f"ffn1_weights.{i}", f1_w), (f"ffn1_biases.{i}", f1_b),
                (f"ffn2_weights.{i}", f2_w), (f"ffn2_biases.{i}", f2_b),
            ):
                self.add_parameter(name_.replace(".", "_"), p)
            self.ln_scales.append(ln_s); self.ln_biases.append(ln_b)
            self.qkv_weights.append(qkv_w); self.qkv_biases.append(qkv_b)
            self.linear_weights.append(lin_w); self.linear_biases.append(lin_b)
            self.ffn_ln_scales.append(fln_s); self.ffn_ln_biases.append(fln_b)
            self.ffn1_weights.append(f1_w); self.ffn1_biases.append(f1_b)
            self.ffn2_weights.append(f2_w); self.ffn2_biases.append(f2_b)

    # ---- per-layer compute
    def _attention(self, i, x, cache, time_step, attn_mask=None):
        b, s, _ = x.shape
        nh, hd = self.num_heads, self.head_dim
        qkv = _qkv_pack(x, self.qkv_weights[i], self.qkv_biases[i])
        q, k, v = qkv.unbind(axis=2)  # [b,s,nh,hd]
        new_cache = None

        def ctx_attention():
            # reference semantics: attn_mask (when given) already encodes
            # causality + padding, so it replaces the built-in causal mask
            if attn_mask is not None:
                return F.scaled_dot_product_attention(
                    q, k, v, attn_mask=attn_mask, dropout_p=0.0, training=False)
            return F.flash_attention(q, k, v, causal=True,
                                     training=self.training)[0]

        from ....ops.pallas.paged_attention import (PagedCacheState,
                                                    PagedKVCache)

        if cache is None:
            out = ctx_attention()
        elif isinstance(cache, (PagedKVCache, PagedCacheState)):
            # paged/block cache (serving path): the manager mutates host-side
            # block tables and functional page arrays; inference-only (no
            # tape node — gradients don't flow through a serving cache)
            from ....ops.pallas.paged_attention import paged_forward

            out_raw, new_cache = paged_forward(cache, q, k, v, time_step,
                                               ctx_attention)
            out = (out_raw if isinstance(out_raw, Tensor)
                   else Tensor._wrap(out_raw))
        elif time_step is None:
            # context phase: write prompt k/v at positions [0, s)
            from ....ops.pallas.decode_attention import cache_prefill_write

            new_cache = apply_op(cache_prefill_write, cache, k, v)
            out = ctx_attention()
        else:
            # decode phase: append this token at time_step, attend over cache
            from ....ops.pallas.decode_attention import cache_decode_step

            out, new_cache = apply_op(
                lambda c, qa, ka, va: cache_decode_step(c, qa, ka, va, time_step),
                cache, q, k, v)
        out = out.reshape([b, s, self.embed_dim])
        out = out.matmul(self.linear_weights[i]) + self.linear_biases[i]
        return out, new_cache

    def _ffn(self, i, x):
        h = _act(self.activation)(x.matmul(self.ffn1_weights[i]) + self.ffn1_biases[i])
        return h.matmul(self.ffn2_weights[i]) + self.ffn2_biases[i]

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, seq_lens=None,
                time_step=None):
        unsupported = {"pre_caches": pre_caches, "rotary_embs": rotary_embs,
                       "seq_lens": seq_lens}
        bad = [k for k, v in unsupported.items() if v is not None]
        if rotary_emb_dims:
            bad.append("rotary_emb_dims")
        if bad:
            raise NotImplementedError(
                f"FusedMultiTransformer: unsupported arguments {bad} — "
                "silently dropping them would compute wrong attention")
        if attn_mask is not None and time_step is not None:
            raise NotImplementedError(
                "FusedMultiTransformer: attn_mask in the decode phase is not "
                "supported (the decode kernel masks by sequence length)")
        x = src
        new_caches: List = []
        for i in range(self.num_layers):
            residual = x
            ln = F.layer_norm(x, [self.embed_dim], self.ln_scales[i],
                              self.ln_biases[i], self.epsilon)
            attn, new_c = self._attention(
                i, ln, None if caches is None else caches[i], time_step,
                attn_mask=attn_mask)
            if caches is not None:
                new_caches.append(new_c if new_c is not None else caches[i])
            x = residual + attn
            residual = x
            ln2 = F.layer_norm(x, [self.embed_dim], self.ffn_ln_scales[i],
                               self.ffn_ln_biases[i], self.epsilon)
            x = residual + self._ffn(i, ln2)
        if caches is not None:
            return x, new_caches
        return x


class FusedTransformerEncoderLayer(nn.Layer):
    """Reference: FusedTransformerEncoderLayer = FusedMultiHeadAttention +
    FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate if attn_dropout_rate is None else attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
