"""paddle.fft parity (reference: python/paddle/fft.py — thin wrappers over
the C++ fft kernels; here jnp.fft, which XLA lowers natively on TPU).
Differentiable through the tape via apply_op."""
from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import Tensor, apply_op

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _wrap1(jnp_fn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda a: jnp_fn(a, n=n, axis=axis, norm=norm), x)

    return op


def _wrap2(jnp_fn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda a: jnp_fn(a, s=s, axes=axes, norm=norm), x)

    return op


def _wrapn(jnp_fn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(lambda a: jnp_fn(a, s=s, axes=axes, norm=norm), x)

    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)
fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def _hfft_nd(x, s, axes, norm, inverse):
    """paddle.fft.hfft2/hfftn family (jnp.fft has only the 1-D hfft):
    full c2c transforms over the leading axes, Hermitian transform on the
    last — the reference's decomposition."""
    axes = tuple(axes)
    lead, last = axes[:-1], axes[-1]
    s_lead = None if s is None else tuple(s)[:-1]
    n_last = None if s is None else tuple(s)[-1]
    if inverse:
        # ihfft consumes the REAL input on the last axis first; the
        # complex ifft over the leading axes follows
        x = jnp.fft.ihfft(x, n=n_last, axis=last, norm=norm)
        if lead:
            x = jnp.fft.ifftn(x, s=s_lead, axes=lead, norm=norm)
        return x
    if lead:
        x = jnp.fft.fftn(x, s=s_lead, axes=lead, norm=norm)
    return jnp.fft.hfft(x, n=n_last, axis=last, norm=norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op(lambda a: _hfft_nd(a, s, axes, norm, False), x)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op(lambda a: _hfft_nd(a, s, axes, norm, True), x)


def _default_axes(a, s, axes):
    if axes is not None:
        return tuple(axes)
    if s is not None:
        # fftn-family convention: with s given, transform the LAST len(s)
        # axes
        return tuple(range(a.ndim - len(s), a.ndim))
    return tuple(range(a.ndim))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    def fn(a):
        return _hfft_nd(a, s, _default_axes(a, s, axes), norm, False)

    return apply_op(fn, x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    def fn(a):
        return _hfft_nd(a, s, _default_axes(a, s, axes), norm, True)

    return apply_op(fn, x)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor._wrap(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor._wrap(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), x)
