"""paddle.fft parity (reference: python/paddle/fft.py — thin wrappers over
the C++ fft kernels; here jnp.fft, which XLA lowers natively on TPU).
Differentiable through the tape via apply_op."""
from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import Tensor, apply_op

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _wrap1(jnp_fn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda a: jnp_fn(a, n=n, axis=axis, norm=norm), x)

    return op


def _wrap2(jnp_fn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda a: jnp_fn(a, s=s, axes=axes, norm=norm), x)

    return op


def _wrapn(jnp_fn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(lambda a: jnp_fn(a, s=s, axes=axes, norm=norm), x)

    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)
fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor._wrap(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor._wrap(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), x)
