"""Online silent-data-corruption audits for the serving engine
(ISSUE 14 tentpole, part b).

The fault stack so far handles LOUD failures: a dispatch dies, a thread
vanishes, a checkpoint is torn — something raises and the machinery of
PRs 6–13 contains it. Silent data corruption is the opposite threat
model ("Cores that don't count", HotOS'21): a flaky core or a flipped
HBM/DRAM bit changes VALUES without changing control flow, and the
engine keeps streaming tokens that are confidently wrong. Before this
module, weights were trusted forever after ``device_put``, cached KV
page bytes were trusted forever after registration, and nothing ever
cross-checked a delivered token. The :class:`IntegritySentinel` closes
those three windows with host-scheduled probes that ride the existing
step loop:

* **Weight audits.** At engine construction the sentinel snapshots a
  blake2b digest per block of every PLACED parameter (the post-
  ``device_put`` bytes — layout- and shard-independent, fetched through
  ``ModelRunner.fetch_param_slice`` so a TP mesh assembles the global
  view). A periodic idle-step probe re-fetches one sampled block and
  compares. Weights never legitimately change while serving, so any
  drift is corruption; containment is the QUARANTINE ladder — the
  watchdog drops ``/readyz``, the router migrates every stream off the
  replica (resume-from-emitted, bit-identical), and the supervised
  restart comes back with freshly verified weights.
* **KV page checksums.** Each cached full block's physical page gets a
  checksum at registration (one tiny jitted reduction over the page's
  K/V lanes across every layer — a scalar per page crosses the wire,
  not the page). A prefix-cache hit re-verifies the matched pages
  BEFORE the splice commits (closing the PR 8 window where page BYTES
  were trusted between the token re-verify and use), and a
  re-registration of an idle refcount-0 page re-verifies its stored
  sum. A mismatch routes through invalidate-on-doubt: the entry and its
  descendants drop, active slots referencing the page are preempted
  (requeue — recompute resumes the stream exactly), and the admission
  recomputes from scratch. Corruption costs a MISS, never a token.
* **Shadow recompute.** Every N steps one sampled greedy decode row is
  re-scored through the model's contiguous (non-paged) forward — an
  independent numeric path — and the delivered token is compared
  against the twin's argmax (tie-aware: an untrained model's near-tie
  margins are not divergence; a corrupted path's are enormous). A
  divergence fails that request with the typed ``IntegrityError``
  instead of letting the stream keep going — kernel/SDC divergence is
  caught online, not in a post-mortem.

Every probe lands in ``paddle_tpu_integrity_checks_total{target}`` /
``paddle_tpu_integrity_failures_total{target}`` (targets ``weights`` /
``kv`` / ``shadow``; the checkpoint layer shares the same pair with
``target="checkpoint"``), so a fleet can alert on "integrity failures
per replica-hour" — the SDC rate the HotOS'21 paper says you must
measure to believe.

All sentinel code is host-side scheduler work between dispatches (never
traced); ``Engine(integrity=None)`` (the default) constructs nothing
and costs nothing.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .errors import IntegrityError

__all__ = ["IntegrityConfig", "IntegritySentinel",
           "count_integrity_check", "bench_integrity_overhead"]


def _counter(name: str, help_: str):
    from ..observability import counter

    return counter(name, help_, labelnames=("target",))


def _count_check(target: str, ok: bool, n: int = 1):
    _counter("paddle_tpu_integrity_checks_total",
             "data-integrity verifications performed, by audit target"
             ).labels(target=target).inc(n)
    if not ok:
        _counter("paddle_tpu_integrity_failures_total",
                 "data-integrity verifications that FAILED, by audit "
                 "target").labels(target=target).inc()


def count_integrity_check(target: str, ok: bool, n: int = 1):
    """Public recording surface for integrity verifications performed
    OUTSIDE the sentinel — the KV host tier's promote-time digest
    compare (ISSUE 15, ``target="kv_tier"``) lands on the same
    ``paddle_tpu_integrity_{checks,failures}_total`` pair the fleet
    alerts on, whether or not an ``IntegritySentinel`` is armed."""
    _count_check(target, ok, n)


class IntegrityConfig:
    """Sentinel knobs. ``mode`` presets:

    * ``"audit"``  — weight audits + KV page checksums (the always-on
      production posture: probes are cheap and detection is containment,
      not crash).
    * ``"strict"`` — audit plus the shadow-recompute sentinel and a
      tighter weight-audit period (the paranoid posture for hosts with
      a known SDC history).

    A dict value for ``Engine(integrity=...)`` starts from the
    ``audit`` preset and overrides per key."""

    __slots__ = ("mode", "weight_audit_every", "weight_blocks",
                 "kv_checksums", "shadow_every", "shadow_tol")

    def __init__(self, mode: str = "audit",
                 weight_audit_every: int = 16, weight_blocks: int = 2,
                 kv_checksums: bool = True, shadow_every: int = 0,
                 shadow_tol: float = 0.05):
        self.mode = mode
        self.weight_audit_every = int(weight_audit_every)
        self.weight_blocks = max(1, int(weight_blocks))
        self.kv_checksums = bool(kv_checksums)
        self.shadow_every = int(shadow_every)
        # tie tolerance, relative to the logit scale: the shadow twin is
        # an independent numeric path, so near-argmax-tie margins (the
        # reason the repo's greedy identity tests are tie-aware) must
        # not count as divergence — real corruption's margins are
        # orders of magnitude past this
        self.shadow_tol = float(shadow_tol)

    @classmethod
    def coerce(cls, spec) -> Optional["IntegrityConfig"]:
        """``Engine(integrity=...)`` front door: None/"off" → no
        sentinel; "audit"/"strict" → preset; dict → audit preset with
        overrides; an IntegrityConfig passes through."""
        if spec is None or spec == "off" or spec is False:
            return None
        if isinstance(spec, cls):
            return spec
        if spec == "audit" or spec is True:
            return cls(mode="audit")
        if spec == "strict":
            return cls(mode="strict", weight_audit_every=8,
                       shadow_every=16)
        if isinstance(spec, dict):
            return cls(**{"mode": "audit", **spec})
        raise ValueError(
            f"integrity={spec!r}: expected None/'off'/'audit'/'strict', "
            "an IntegrityConfig, or a dict of its fields")


def _page_sums_raw(bufs, idx):
    """The tiny jitted per-page checksum reduction: for each physical
    page in ``idx``, a position-weighted f32 sum over that page's bytes
    in EVERY layer's K/V (and scale) buffer. Deterministic for a fixed
    backend+shape (jit fixes the reduction order), so equality is an
    exact content check; a single flipped bit shifts at least one
    weighted term. One scalar per page crosses the device boundary —
    the page bytes never do."""
    out = jnp.zeros(idx.shape[0], jnp.float32)
    for j, b in enumerate(bufs):
        sel = b[idx].astype(jnp.float32).reshape(idx.shape[0], -1)
        w = 1.0 + (jnp.arange(sel.shape[1], dtype=jnp.float32) % 911.0)
        out = out + (j + 1) * jnp.sum(sel * w, axis=1)
    return out


def _pow2ceil(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class IntegritySentinel:
    """Engine-owned SDC auditor; see module docstring. Construction
    snapshots the weight digest baseline (the weights are verified-fresh
    at that moment: just loaded/placed), so it happens LAST in
    ``Engine.__init__``."""

    def __init__(self, engine, cfg: IntegrityConfig):
        self.engine = engine
        self.cfg = cfg
        self.last_error: Optional[IntegrityError] = None
        self._steps = 0
        self._since_audit = 0
        self._probe_cursor = 0
        self._shadow_cursor = 0
        self._page_sum: Dict[int, float] = {}
        self._sum_fn = jax.jit(_page_sums_raw)
        # weight baseline: per param, (element_count, [(a, b, digest)])
        self._weight_base: List[Tuple[int, List[Tuple[int, int, str]]]] = []
        self._probe_targets: List[Tuple[int, int]] = []  # (param, block)
        if cfg.weight_audit_every:
            self._snapshot_weights()

    @classmethod
    def build(cls, engine, spec) -> Optional["IntegritySentinel"]:
        cfg = IntegrityConfig.coerce(spec)
        return None if cfg is None else cls(engine, cfg)

    # ------------------------------------------------------- weight audit
    def _snapshot_weights(self):
        """Digest every placed parameter block-wise from the bytes the
        compiled programs will actually consume (``engine._params``,
        fetched through the runner so a TP mesh assembles the global
        view)."""
        nb = self.cfg.weight_blocks
        for i in range(len(self.engine._params)):
            host = self.engine.runner.fetch_param_slice(i, 0, None)
            n = int(host.size)
            raw = host.tobytes()
            itemsize = host.dtype.itemsize
            blocks: List[Tuple[int, int, str]] = []
            per = max(1, -(-n // nb))
            for b in range(0, n, per):
                a, e = b, min(n, b + per)
                dg = hashlib.blake2b(raw[a * itemsize:e * itemsize],
                                     digest_size=16).hexdigest()
                blocks.append((a, e, dg))
                self._probe_targets.append((i, len(blocks) - 1))
            self._weight_base.append((n, blocks))

    def audit_weights_once(self) -> bool:
        """Probe ONE (param, block): re-fetch the shard slice and
        compare its digest against the load-time baseline. Returns
        False — after quarantining the engine — on a mismatch."""
        if not self._probe_targets:
            return True
        i, b = self._probe_targets[
            self._probe_cursor % len(self._probe_targets)]
        self._probe_cursor += 1
        a, e, want = self._weight_base[i][1][b]
        fi = self.engine._fi
        if fi is not None and fi.fire("bit-flip-weight"):
            self._flip_weight_bit(i, a, e, fi)
        got = hashlib.blake2b(
            self.engine.runner.fetch_param_slice(i, a, e).tobytes(),
            digest_size=16).hexdigest()
        ok = got == want
        _count_check("weights", ok)
        if not ok:
            err = IntegrityError(
                f"weight audit digest mismatch: param {i} elements "
                f"[{a}, {e}) no longer match the load-time baseline — "
                "silent weight corruption; quarantining the engine")
            self.last_error = err
            # containment ladder, weight arm: readiness drops, the
            # router drains/migrates, the supervised restart reloads
            # verified weights
            self.engine._watchdog.quarantine(err)
        return ok

    def _flip_weight_bit(self, i: int, a: int, e: int, fi):
        """``bit-flip-weight`` damage: XOR one seed-chosen bit of one
        seed-chosen element inside the block the NEXT probe will fetch
        (so a single fire is always observable), written back through a
        sharding-preserving scatter."""
        p = self.engine._params[i]
        flat = a + fi.draw("bit-flip-weight", max(1, e - a))
        idx = np.unravel_index(flat, p.shape) if p.ndim else ()
        val = np.asarray(jax.device_get(p[idx]))
        raw = bytearray(val.tobytes())
        bit = fi.draw("bit-flip-weight", 8 * len(raw))
        raw[bit // 8] ^= 1 << (bit % 8)
        new = np.frombuffer(bytes(raw), dtype=val.dtype).reshape(val.shape)
        self.engine._params[i] = p.at[idx].set(jnp.asarray(new))

    # -------------------------------------------------- KV page checksums
    def _page_sums(self, pages: List[int]) -> List[float]:
        m = len(pages)
        idx = np.zeros((_pow2ceil(m),), np.int32)
        idx[:m] = pages
        vals = np.asarray(jax.device_get(
            self._sum_fn(self.engine._pages_flat(), jnp.asarray(idx))))
        return [float(v) for v in vals[:m]]

    def note_registered(self, pages: List[int]) -> List[int]:
        """Checksum freshly registered cache pages; for a page that
        ALREADY carries a sum (an idle refcount-0 block re-registered by
        a later identical prompt) the stored sum is re-verified instead
        — corruption of a parked page is caught at the earliest touch.
        Returns the pages that FAILED (caller contains)."""
        if not self.cfg.kv_checksums or not pages:
            return []
        sums = self._page_sums([int(p) for p in pages])
        bad: List[int] = []
        for pg, s in zip(pages, sums):
            pg = int(pg)
            old = self._page_sum.get(pg)
            if old is None:
                self._page_sum[pg] = s
                continue
            ok = old == s
            _count_check("kv", ok)
            if not ok:
                bad.append(pg)
        if bad:
            self.last_error = IntegrityError(
                f"KV page checksum mismatch at re-registration: pages "
                f"{bad} changed while parked in the prefix cache")
        return bad

    def verify_pages(self, pages: List[int]) -> List[int]:
        """The splice-time probe: re-reduce every matched page that has
        a stored checksum and compare exactly. Returns the bad pages —
        the caller invalidates and recomputes, so a flipped page bit
        costs a cache miss, never a wrong token."""
        if not self.cfg.kv_checksums:
            return []
        known = [int(p) for p in pages if int(p) in self._page_sum]
        if not known:
            return []
        sums = self._page_sums(known)
        bad: List[int] = []
        for pg, s in zip(known, sums):
            ok = self._page_sum[pg] == s
            _count_check("kv", ok)
            if not ok:
                bad.append(pg)
        if bad:
            self.last_error = IntegrityError(
                f"KV page checksum mismatch at splice: pages {bad} "
                "changed between registration and reuse")
        return bad

    def forget_page(self, page: int):
        """The page left the cache (eviction, invalidation, realloc for
        new content) — its stored sum no longer describes anything."""
        self._page_sum.pop(int(page), None)

    def sum_of_page(self, page: int) -> Optional[float]:
        """The stored device-side checksum for ``page`` (None when the
        page was never registered). The KV host tier reads it at
        demotion so the sum can travel with the spilled bytes
        (ISSUE 15)."""
        return self._page_sum.get(int(page))

    def adopt_page_sum(self, page: int, s: float):
        """Checksum-verified promotion (ISSUE 15): the tier restored a
        page whose bytes hash-matched their demotion-time digest, so
        the device-side sum recorded before the round trip describes
        the new physical page too — re-adopting it keeps the splice-
        time probe (:meth:`verify_pages`) guarding promoted pages
        exactly like never-demoted ones."""
        self._page_sum[int(page)] = float(s)

    def reset_kv(self):
        """Pool reset: the buffers (and every checksum over them) died."""
        self._page_sum.clear()

    # ---------------------------------------------------- shadow recompute
    def shadow_check(self) -> Optional[bool]:
        """Re-score one sampled greedy decode row through the model's
        contiguous (non-paged) forward — an independent numeric path —
        and compare the delivered last token against the twin's argmax,
        tie-aware (``shadow_tol`` of the logit scale). A divergence is
        kernel/SDC corruption caught ONLINE: that request fails typed
        (``integrity``) instead of streaming on."""
        eng = self.engine
        cands = [r for r in eng._active.values()
                 if r.temperature == 0.0 and r.tokens and not r.done]
        if not cands:
            return None
        req = cands[self._shadow_cursor % len(cands)]
        self._shadow_cursor += 1
        hist = req.tokens[:-1]
        ids = (np.concatenate([req.prompt,
                               np.asarray(hist, np.int32)])
               if hist else np.asarray(req.prompt, np.int32))
        from ..framework.tensor import Tensor, pause_tape

        with pause_tape():
            logits = eng.model.forward(
                Tensor._wrap(jnp.asarray(ids[None, :])))
        lg = logits._data if isinstance(logits, Tensor) else logits
        row = np.asarray(jax.device_get(lg[0, -1].astype(jnp.float32)))
        delivered = int(req.tokens[-1])
        top = float(row.max())
        margin = top - float(row[delivered])
        scale = max(1.0, abs(top))
        ok = margin <= self.cfg.shadow_tol * scale
        _count_check("shadow", ok)
        if not ok:
            err = IntegrityError(
                f"shadow recompute divergence: request {req.rid} "
                f"delivered token {delivered} but the contiguous twin "
                f"argmaxes {int(row.argmax())} (margin {margin:.4f} at "
                f"scale {scale:.4f}) — kernel/SDC divergence",
                rid=req.rid)
            self.last_error = err
            eng._fail_request(req, err)
        return ok

    # ------------------------------------------------------------ driver
    def on_step(self) -> None:
        """The engine's per-step hook (host side, after a successful
        step). Weight audits prefer IDLE steps — nothing queued — but a
        sustained-load engine still audits at 4x the period, so a busy
        replica cannot dodge its own probes forever. Never raises: a
        probe blowing up must not fault the serving step it rides."""
        self._steps += 1
        try:
            cfg = self.cfg
            if cfg.weight_audit_every and not \
                    self.engine._watchdog.quarantined:
                self._since_audit += 1
                idle = not self.engine._queue
                if self._since_audit >= cfg.weight_audit_every and (
                        idle or self._since_audit
                        >= 4 * cfg.weight_audit_every):
                    self._since_audit = 0
                    self.audit_weights_once()
            if cfg.shadow_every and self._steps % cfg.shadow_every == 0:
                self.shadow_check()
        except Exception as e:  # noqa: BLE001 - probe isolation
            self._note_probe_fault(e)

    def _note_probe_fault(self, exc: BaseException):
        """A probe itself failed (not a detection — the probe broke).
        Routed to the taxonomy counters as a failed ``sentinel`` check
        so it is scrape-visible rather than silently absorbed."""
        err = IntegrityError(
            f"integrity probe raised {type(exc).__name__}: {exc}")
        err.__cause__ = exc
        self.last_error = err
        _count_check("sentinel", False)


# --------------------------------------------------------------- benchmark
def bench_integrity_overhead(cfg, on_tpu: bool):
    """bench.py ``bench_integrity`` block (ISSUE 14 satellite): the
    audit layer's steady-state cost as an interleaved-rep ratio of
    median scheduling-step times, sentinel ``strict`` vs off, over the
    same prefix-heavy workload (so the KV checksum path actually
    exercises). Per-engine medians are floored at the host jitter floor
    (50 ms on the single-core CPU smoke host, 20 ms on TPU — memory:
    one cold compile lands in p99 otherwise) before the ratio, and the
    gate is ``integrity_overhead_frac`` (median-on / median-off - 1)
    < 2%."""
    import time

    from ..models.gpt import GPTConfig, GPTForCausalLM
    from ..observability import metric_total
    from .engine import Engine

    del cfg  # the block sizes its own tiny config (CPU smoke parity)
    from .. import seed as _seed

    _seed(0)
    mcfg = GPTConfig(hidden_size=128, num_layers=2, num_heads=4,
                     max_position=256, vocab_size=1024)
    model = GPTForCausalLM(mcfg)
    model.eval()

    rng = np.random.default_rng(7)
    shared = rng.integers(0, 1024, (32,))

    def workload(eng):
        # prefix-heavy (shared 32-token template + per-request tail):
        # splice/register probes fire on the hit path, not just misses
        reqs = []
        for i in range(4):
            tail = rng.integers(0, 1024, (4 + i,))
            reqs.append(eng.add_request(
                np.concatenate([shared, tail]), 8))
        return reqs

    engines = {
        "off": Engine(model, max_slots=4, num_pages=128, page_size=8,
                      chunk_size=4, dtype=jnp.float32, prefix_cache=True,
                      integrity=None),
        "on": Engine(model, max_slots=4, num_pages=128, page_size=8,
                     chunk_size=4, dtype=jnp.float32, prefix_cache=True,
                     integrity={"mode": "strict", "weight_audit_every": 4,
                                "shadow_every": 8}),
    }
    checks0 = metric_total("paddle_tpu_integrity_checks_total")
    fails0 = metric_total("paddle_tpu_integrity_failures_total")
    # warmup: compile every program both engines will touch
    for eng in engines.values():
        workload(eng)
        eng.run()
    reps, steps = 4, {"off": [], "on": []}
    for _ in range(reps):
        for key, eng in engines.items():
            workload(eng)
            while True:
                t0 = time.perf_counter()
                live = eng.step()
                steps[key].append(time.perf_counter() - t0)
                if not live:
                    break
    floor_s = (0.020 if on_tpu else 0.050)
    med_off = float(np.median(steps["off"]))
    med_on = float(np.median(steps["on"]))
    ratio = max(med_on, floor_s) / max(med_off, floor_s)
    overhead = max(0.0, ratio - 1.0)
    checks = int(metric_total("paddle_tpu_integrity_checks_total")
                 - checks0)
    fails = int(metric_total("paddle_tpu_integrity_failures_total")
                - fails0)
    ok = overhead < 0.02 and fails == 0 and checks > 0
    if not ok:
        print(f"WARNING: bench_integrity gate failed: overhead="
              f"{overhead:.4f} (<0.02 required), checks={checks} (>0), "
              f"failures={fails} (==0)")
    return {
        "integrity_overhead_frac": round(overhead, 4),
        "integrity_step_ms_off": round(1e3 * med_off, 3),
        "integrity_step_ms_on": round(1e3 * med_on, 3),
        "integrity_jitter_floor_ms": 1e3 * floor_s,
        "integrity_bench_checks": checks,
        "integrity_bench_failures": fails,
        "integrity_ok": bool(ok),
    }
