"""Cache-coordinator layer of the serving engine (ISSUE 11 tentpole).

Owns the paged KV pool and everything that hands pages around:

* the DEVICE page buffers (``k_pages``/``v_pages``/``scale_pages`` per
  layer) — physically partitioned across the TP axis when the
  model-runner is sharded (each shard holds its KV heads' lanes of
  every page: layout ``[P, page_size, (Hkv/tp)*D]`` per shard);
* the HOST-GLOBAL allocator: block tables, lengths, the per-page
  refcounts, free lists — one copy, device-count-agnostic, so PR 8's
  refcount/COW prefix-cache logic runs untouched whatever the mesh;
* the prefix cache and the pending copy-on-write set;
* pool reset for whole-step fault recovery — donated-dead buffers
  rebuild PER-SHARD through the runner (a replicated host rebuild
  would silently unshard the pool: the single-chip assumption this
  split surfaced, ISSUE 11 satellite).

This is also the prefill→decode handoff point of the disaggregated
scheduler: a prefill-role step writes a prompt's pages into the shared
pool and the decode-role batch picks the slot up at the very next
boundary — streaming KV by table reference, never by copy (the
DistServe-shaped move without its cross-worker transfer, because the
pool is one sharded buffer).

Engine-core reaches all of this through thin delegating properties, so
the scheduler code (and its tests) read exactly as before the split.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from .prefix_cache import PrefixCache

__all__ = ["CacheCoordinator"]


class CacheCoordinator:
    """Paged KV pool + allocator; see module docstring."""

    def __init__(self, engine, prefix_cache: bool = False,
                 kv_host_pages: int = 0):
        self.engine = engine
        cfg = engine.cfg
        self.num_pages = engine.num_pages
        self.page_size = engine.page_size
        # host-global allocator state; page 0 reserved as the trash page
        self.tables = np.zeros(
            (engine.max_slots, engine.max_pages_per_seq), np.int32)
        self.lengths = np.zeros((engine.max_slots,), np.int32)
        self.page_ref = np.zeros((self.num_pages,), np.int32)
        self.pcache = PrefixCache(self.page_size) if prefix_cache else None
        # host-DRAM spill tier (ISSUE 15): eviction of idle cached pages
        # becomes an async demotion and a later hash-chain hit an async
        # checksum-verified promotion — effective cache capacity grows
        # to the host slab without the engine thread ever blocking on a
        # device<->host page copy
        self.tier = None
        if kv_host_pages:
            if self.pcache is None:
                raise ValueError(
                    "kv_host_pages > 0 requires prefix_cache=True (the "
                    "host tier spills idle PREFIX-CACHE pages; without "
                    "the cache there is nothing to demote)")
            from .kv_tier import HostTier

            self.tier = HostTier(self, kv_host_pages)
        self.cow_pending: List = []  # (src, dst) device copies owed
        self.free_pages: List[int] = []
        self.free_slots: List[int] = []
        self.k_pages: List = []
        self.v_pages: List = []
        self.scale_pages: List = []
        self.reset()

    # ------------------------------------------------------------ pool
    def reset(self):
        """(Re)create the device page buffers and allocator free lists.
        Construction AND whole-step fault recovery: after a failed
        dispatch the donated buffers may be dead, but their content is
        recomputable (every requeued request re-prefills), so a fresh
        zeroed pool loses nothing. The buffers are placed through the
        model-runner so a sharded pool rebuilds per-shard."""
        eng = self.engine
        cfg = eng.cfg
        ig = getattr(eng, "_integrity", None)
        if ig is not None:
            # page checksums describe buffers that are about to die
            # (ISSUE 14); getattr because construction-time reset runs
            # before the engine builds its sentinel
            ig.reset_kv()
        n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
        store = jnp.int8 if eng.quantized else eng.dtype
        shape = (self.num_pages, self.page_size, n_kv * cfg.head_dim)
        place = eng.runner.place_pages
        self.k_pages = place([jnp.zeros(shape, store)
                              for _ in range(cfg.num_layers)])
        self.v_pages = place([jnp.zeros(shape, store)
                              for _ in range(cfg.num_layers)])
        if eng.quantized:
            sshape = (self.num_pages, self.page_size, 128)
            self.scale_pages = [jnp.zeros(sshape, jnp.bfloat16)
                                for _ in range(cfg.num_layers)]
        else:
            self.scale_pages = [None] * cfg.num_layers
        self.tables[:] = 0
        self.lengths[:] = 0
        self.free_pages = list(range(self.num_pages - 1, 0, -1))
        self.free_slots = list(range(eng.max_slots - 1, -1, -1))
        # the prefix cache maps token hashes to PAGE CONTENT — content
        # that just died with the buffers; flush it and every refcount.
        # The host tier flushes with it (ISSUE 15): its copies were
        # captured from the pool that just died mid-fault, and spill
        # state that predates a fault is never served.
        self.page_ref[:] = 0
        if self.pcache is not None:
            self.pcache.clear()
        if self.tier is not None:
            self.tier.reset()
        self.cow_pending = []

    def pages_flat(self) -> List:
        out = list(self.k_pages) + list(self.v_pages)
        if self.engine.quantized:
            out += list(self.scale_pages)
        if self.tier is not None:
            # queued demotions capture NOW, before whatever dispatch
            # asked for the buffers can overwrite the surrendered pages
            # (every program reaches the pool through this call — the
            # same choke-point guarantee _flush_cow leans on)
            self.tier.flush_captures(out)
        return out

    def set_pages(self, pages_flat):
        """Host-side writeback after a jitted call returns."""
        L = self.engine.cfg.num_layers
        self.k_pages = list(pages_flat[:L])
        self.v_pages = list(pages_flat[L:2 * L])
        if self.engine.quantized:
            self.scale_pages = list(pages_flat[2 * L:3 * L])

    # ------------------------------------------------------- allocator
    def alloc_page(self) -> Optional[int]:
        """Claim one physical page (refcount 1): free list first, then
        LRU reclamation of an idle prefix-cache page — cached pages are
        reclaimed BEFORE any active request is preempted. With the host
        tier armed (ISSUE 15) reclamation DEMOTES instead of evicting:
        the victim's bytes start their async spill to host DRAM (the
        capture gather is dispatched before the page changes owner) and
        the chain entry survives, promotable on a later hit."""
        if self.free_pages:
            page = self.free_pages.pop()
        elif self.pcache is not None:
            if self.tier is not None:
                taken = self.pcache.take_for_demotion(self.page_ref)
                if taken is None:
                    return None
                page, ent = taken
                self.tier.demote(page, ent)
            else:
                page = self.pcache.evict_lru(self.page_ref)
                if page is None:
                    return None
            m = self.engine._m
            if m is not None:
                m.pc_evictions.inc()
        else:
            return None
        self.page_ref[page] = 1
        ig = getattr(self.engine, "_integrity", None)
        if ig is not None:
            # the page is being handed to a NEW owner: any checksum
            # recorded for its previous (cached) content is stale now —
            # keeping it would fail the next registration vacuously
            ig.forget_page(page)
        return page

    def release_page(self, page: int):
        """Drop one reference; at refcount 0 the page returns to the
        free list unless the prefix cache still maps content to it (it
        then stays resident, LRU-evictable). The single release choke
        point — shared pages can never double-free."""
        page = int(page)
        if page <= 0:
            return
        ref = int(self.page_ref[page]) - 1
        assert ref >= 0, f"page {page} refcount went negative"
        self.page_ref[page] = ref
        if ref == 0 and not (self.pcache is not None
                             and self.pcache.contains_page(page)):
            self.free_pages.append(page)

    def available_pages(self) -> int:
        """Pages an allocation burst could claim (free + idle cached —
        an upper bound, see evictable_count)."""
        n = len(self.free_pages)
        if self.pcache is not None:
            n += self.pcache.evictable_count(self.page_ref)
        return n

    # ------------------------------------------------------- host tier
    def drain_tier(self):
        """Apply the spill worker's completions (no-op without a tier):
        finished demotions become host-resident entries, verified
        promotions splice back into the pool. Engine thread only —
        called at step/admission boundaries."""
        if self.tier is not None:
            self.tier.drain()

    def shutdown_tier(self):
        """Stop the spill worker (frontend drain/shutdown, replica
        quarantine/restart). Idempotent no-op without a tier."""
        if self.tier is not None:
            self.tier.stop()

    # ------------------------------------------------ cluster handoff
    def export_handoff(self, tokens) -> Optional[dict]:
        """Capture the prompt's cached KV pages into a wire payload for
        a cross-replica handoff (ISSUE 20) — the prefill side of the
        prefill/decode pool split. Engine thread; blocks on the
        device→host fetch (delegated to the spill-named kv_tier helper,
        the designated blocking-copy site), so the cluster layer must
        reach it through ``ServingFrontend.call`` from its handoff
        thread. None when nothing is cached for the prompt."""
        if self.pcache is None:
            return None
        from .kv_tier import capture_handoff_spill

        return capture_handoff_spill(self.engine, tokens)

    # ----------------------------------------------------- COW / faults
    def flush_cow(self, copy_fn):
        """Flush pending copy-on-write page duplications in one device
        dispatch — owed BEFORE any program writes into a spliced table.
        ``copy_fn(pages_flat, src, dst) -> pages_flat`` is the engine's
        donated jit helper (sharding-preserving: page-index scatters
        never touch the lane axis the pool shards on)."""
        if self.cow_pending:
            src = np.asarray([s for s, _ in self.cow_pending], np.int32)
            dst = np.asarray([d for _, d in self.cow_pending], np.int32)
            self.set_pages(copy_fn(self.pages_flat(), jnp.asarray(src),
                                   jnp.asarray(dst)))
            self.cow_pending = []

    def corrupt_page(self, page: int):
        """``prefix-cache-corruption`` fault-injection damage: garbage
        layer-0 K rows for one cached page (safe — pages are only read
        below ``lengths``; see Engine._corrupt_page docstring history)."""
        eng = self.engine
        garbage = jnp.full(self.k_pages[0].shape[1:],
                           57 if eng.quantized else 1e3,
                           self.k_pages[0].dtype)
        self.k_pages[0] = self.k_pages[0].at[int(page)].set(garbage)
