"""Graceful degradation for the paged serving engine (ISSUE 6 tentpole,
part 3): a watchdog that detects repeated step failures, drafter faults,
and drafter-acceptance collapse, and DOWNGRADES the engine instead of
letting it die — then probes its way back up once the storm passes.

Degraded-mode state machine (one axis, monotone levels)::

    0 HEALTHY      spec decode on (if configured), full admission cap
    1 NO_SPEC      spec decode forced off -> vanilla chained decode
                   (greedy output identical by construction - PR 5's
                   correctness invariant survives degradation)
    2 SMALL_BATCH  admission cap halved on top of NO_SPEC: fewer slots,
                   less page pressure, smaller blast radius per step

Transitions DOWN happen when a fault counter crosses its threshold:
``step_fault_threshold`` consecutive whole-step faults, or
``drafter_fault_threshold`` consecutive drafter faults, or a full
acceptance window whose draft-acceptance rate sits below
``accept_floor`` (drafting is pure overhead at that point). Transitions
UP are recovery probes: after ``recover_after`` consecutive healthy
steps the level steps back toward HEALTHY one notch at a time, with the
fault counters and acceptance window cleared so a relapse is judged on
fresh evidence, not the stale storm.

The current level is exported as the ``paddle_tpu_engine_degraded``
gauge (0/1/2), so dashboards can alert on "engine survived but is
running degraded" — the state the whole layer exists to make reachable.
All of this is host-side scheduler code; nothing here is ever traced.

Thread contract (audited for tpurace, ISSUE 19): every state-mutating
method (``note_*``, ``quarantine``, ``_degrade``/``_recover``/
``_apply``) runs on the engine thread — the step loop and the
integrity sentinel both live there. The only cross-thread surface is
read-only: ``ready``/``readiness()`` polled by the asyncio server and
the router supervisor, over GIL-atomic ints/bools, with
``quarantined`` a monotone latch (False→True once, never back), so a
torn read is impossible and a momentarily stale one only delays the
routing reaction by a poll interval.

**Quarantine (ISSUE 14)** is an orthogonal, STICKY axis on top of the
levels: when the integrity sentinel proves the engine's own state is
corrupt (a weight-audit digest mismatch — the weights every future
token flows through), degrading throughput is the wrong tool. The
engine is marked quarantined: readiness drops immediately (``/readyz``
→ 503), the multi-replica router migrates every in-flight stream off
and schedules a supervised restart, and — unlike the levels — nothing
probes back up: only a fresh engine with re-verified weights clears it,
because the corrupt copy can never re-earn trust from inside.
"""
from __future__ import annotations

from collections import deque
from typing import Optional

__all__ = ["Watchdog", "HEALTHY", "NO_SPEC", "SMALL_BATCH"]

HEALTHY, NO_SPEC, SMALL_BATCH = 0, 1, 2
_LEVEL_NAMES = {HEALTHY: "healthy", NO_SPEC: "no-spec",
                SMALL_BATCH: "small-batch"}


class Watchdog:
    def __init__(self, engine, step_fault_threshold: int = 3,
                 drafter_fault_threshold: int = 3,
                 accept_floor: float = 0.05, accept_window: int = 32,
                 recover_after: int = 64):
        self.engine = engine
        self.step_fault_threshold = int(step_fault_threshold)
        self.drafter_fault_threshold = int(drafter_fault_threshold)
        self.accept_floor = float(accept_floor)
        self.recover_after = int(recover_after)
        self.level = HEALTHY
        self.quarantined = False           # sticky integrity quarantine
        self.quarantine_cause: Optional[BaseException] = None
        self.last_fault: Optional[BaseException] = None
        self._consec_step_faults = 0
        self._consec_drafter_faults = 0
        self._healthy_steps = 0
        # (proposed, accepted) per spec step; collapse is judged over a
        # FULL window so one unlucky batch can't flap the mode
        self._accept = deque(maxlen=int(accept_window))
        self._apply()

    # ------------------------------------------------------------ events
    def note_step_ok(self):
        """A scheduling step completed without an engine-level fault."""
        self._consec_step_faults = 0
        self._healthy_steps += 1
        if self.level > HEALTHY and self._healthy_steps >= self.recover_after:
            self._recover()

    def note_step_fault(self, exc: BaseException):
        """A whole-step fault (dispatch died / host spine raised)."""
        self.last_fault = exc
        self._healthy_steps = 0
        self._consec_step_faults += 1
        if self._consec_step_faults >= self.step_fault_threshold:
            self._consec_step_faults = 0
            self._degrade()

    def note_drafter_fault(self):
        """The spec drafter raised; the step fell back to zero drafts."""
        self._healthy_steps = 0
        self._consec_drafter_faults += 1
        if self._consec_drafter_faults >= self.drafter_fault_threshold:
            self._consec_drafter_faults = 0
            if self.level < NO_SPEC:
                self.level = NO_SPEC
                self._apply()

    def note_drafter_ok(self):
        self._consec_drafter_faults = 0

    def note_acceptance(self, proposed: int, accepted: int):
        """One spec step's batch-wide draft acceptance. A full window
        under ``accept_floor`` means drafting burns a dispatch per step
        for nothing — degrade to vanilla, recover-probe later."""
        if proposed <= 0:
            return
        self._accept.append((proposed, accepted))
        if len(self._accept) < self._accept.maxlen:
            return
        prop = sum(p for p, _ in self._accept)
        acc = sum(a for _, a in self._accept)
        if prop > 0 and acc / prop < self.accept_floor \
                and self.level < NO_SPEC:
            self._accept.clear()
            self.level = NO_SPEC
            self._apply()

    def quarantine(self, cause: Optional[BaseException] = None):
        """Integrity corruption proven (ISSUE 14): drop readiness NOW
        and stay down. Sticky by design — see module docstring; the
        router's quarantine arm migrates streams and restarts the
        replica, and the restarted engine's fresh watchdog starts
        clean."""
        self.quarantined = True
        self.quarantine_cause = cause
        self.last_fault = cause if cause is not None else self.last_fault
        # flight recorder (ISSUE 18): quarantine is fail-stop — dump the
        # trace ring's last-N-seconds postmortem while it still shows
        # the steps that led here (no-op when tracing is off)
        from ..observability.tracing import flight_record

        flight_record("quarantine-"
                      + (type(cause).__name__ if cause else "manual"))
        self._apply()

    # ----------------------------------------------------- state machine
    def _degrade(self):
        if self.level < SMALL_BATCH:
            self.level += 1
            self._apply()

    def _recover(self):
        self.level -= 1
        self._healthy_steps = 0
        self._consec_step_faults = 0
        self._consec_drafter_faults = 0
        self._accept.clear()
        self._apply()

    # ------------------------------------------------------- readiness
    @property
    def ready(self) -> bool:
        """Readiness for NEW traffic (ISSUE 13): liveness is the
        process/thread being up (the supervisor's job, not ours);
        readiness is this state machine judging the engine fit to take
        MORE work. NO_SPEC still serves at full admission capacity
        (drafting off costs throughput, not correctness), so it stays
        ready; SMALL_BATCH means the engine is actively shedding load —
        a router should stop sending it new streams and let it recover
        while in-flight work completes. A quarantined engine (integrity
        corruption, ISSUE 14) is never ready, whatever its level."""
        return not self.quarantined and self.level < SMALL_BATCH

    def readiness(self) -> dict:
        """The structured readiness snapshot ``/readyz`` and the
        multi-replica router consume. ``quarantined`` is the router's
        cue to migrate in-flight streams too, not just stop routing new
        ones — the corrupt weights poison EXISTING streams' future
        tokens, unlike an ordinary degraded level."""
        return {"ready": self.ready, "level": self.level,
                "mode": self.mode, "quarantined": self.quarantined}

    def _apply(self):
        eng = self.engine
        eng._spec_enabled = self.level < NO_SPEC
        cap = (eng.max_slots if self.level < SMALL_BATCH
               else max(1, eng.max_slots // 2))
        # mesh-aligned batch shrink (ISSUE 11 satellite): a sharded
        # engine quantizes compiled-program shapes to _batch_quantum
        # (the TP degree) — a degraded cap that drops off that grid
        # would make every post-degradation step a novel bucket shape
        # (a recompile storm exactly when the engine is least healthy),
        # so round the halved cap UP to the quantum, clamped at
        # max_slots. Single-chip engines have quantum 1: unchanged.
        q = max(1, int(getattr(eng, "_batch_quantum", 1)))
        if q > 1:
            cap = min(eng.max_slots, -(-cap // q) * q)
        eng._slot_cap = cap
        if eng._m is not None:
            eng._m.degraded.set(self.level)
            eng._m.ready.set(1 if self.ready else 0)

    @property
    def mode(self) -> str:
        return "quarantined" if self.quarantined \
            else _LEVEL_NAMES[self.level]
