"""Model-runner layer of the serving engine (ISSUE 11 tentpole).

The engine split is engine-core / model-runner / cache-coordinator:

* **engine-core** (``engine.Engine``) — the host scheduler: admission,
  slot bookkeeping, harvest, retries, watchdog. Device-count-agnostic;
  it never mentions a mesh.
* **model-runner** (this module) — owns the COMPILED programs (prefill,
  decode chain, mixed chunk+decode, spec verify) and, when ``tp > 1``,
  the tensor-parallel mesh they trace under: weights column/row-sharded
  over the ``tp`` axis via ``shard_map``, the paged KV pool sharded
  along its KV-head lanes, host-built operands (ids, tables, lengths,
  temps, keys) replicated. ``tp=None``/1 builds exactly the single-chip
  programs — bit-compatible with the pre-split engine.
* **cache-coordinator** (``cache_coord.CacheCoordinator``) — the paged
  pool + allocator; pages physically partitioned across the TP axis,
  page tables host-global.

Sharding layout (the vLLM/Megatron TP plan, rebuilt JAX-idiomatically
as ONE ``shard_map`` region per dispatched program — no per-step
reshard boundary, which is exactly what tpushard TPC502 gates):

==============================  =========================  ============
tensor                          global shape               spec
==============================  =========================  ============
q/k/v/gate/up projection w      [H, out]                   P(None, 'tp')
o/down projection w             [in, H]                    P('tp', None)
column-parallel bias            [out]                      P('tp')
embeddings, norms, lm_head      (any)                      P() replicated
KV pages (per layer, k and v)   [P, page_size, Hkv*D]      P(None, None, 'tp')
ids/tables/lengths/temps/keys   (any)                      P() replicated
==============================  =========================  ============

Inside the region each shard computes its head/FF slice; the Megatron
``g`` collectives (one ``psum`` after the attention output projection
and one after the MLP down projection, per layer) are inserted by the
model's ``_tp_axis`` hook, which :meth:`ModelRunner.local_view` arms
only for the duration of the trace. Activations stay replicated across
``tp`` at the program boundary, so tokens/keys/bad flags come back with
``out_specs=P()`` and the host scheduler reads them exactly as in the
single-chip engine.

Static gating: :meth:`ModelRunner.traceable` returns the UNJITTED
shard_map-wrapped program, which the tpucheck registry traces
(``tools/analyze_tpu.py`` entries ``tp_sharded_decode_step`` /
``tp_sharded_mixed_step``) — the comm plan is verified clean (TPC501/
502/503, TPC601 roofline) before any multi-device run.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ModelRunner"]

# projection leaves by the layer attribute that owns them (duck-typed —
# any model family exposing the llama-style separate projections shards)
_COL_LAYERS = ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj")
_ROW_LAYERS = ("o_proj", "down_proj")
# stacked expert weights ([E, ...] leading expert dim) shard over 'ep';
# the router stays replicated so routing is identical on every shard
_EXPERT_LEAVES = ("experts_gate", "experts_up", "experts_down")


class ModelRunner:
    """Builds and caches the engine's compiled programs; owns the TP/EP
    mesh and sharding specs when ``tp > 1`` or ``ep > 1`` (see module
    docstring)."""

    AXIS = "tp"
    EP_AXIS = "ep"

    def __init__(self, engine, tp: Optional[int] = None,
                 ep: Optional[int] = None):
        self.engine = engine
        self.tp = int(tp) if tp else 1
        self.ep = int(ep) if ep else 1
        self.mesh = None
        self.param_specs: Optional[List] = None
        # compiled-program caches (moved here from the monolithic Engine;
        # engine-core reaches them through delegating properties)
        self.decode_fns: Dict[Tuple, object] = {}
        self.prefill_fns: Dict[Tuple, object] = {}
        self.mixed_fns: Dict[Tuple, object] = {}
        if self.tp > 1 or self.ep > 1:
            self._validate_and_build_mesh()

    # ------------------------------------------------------------- mesh
    def _validate_and_build_mesh(self):
        from jax.sharding import Mesh

        cfg = self.engine.cfg
        tp, ep = self.tp, self.ep
        if tp > 1 and self.engine.quantized:
            raise NotImplementedError(
                "tp > 1 with quantized_cache: the int8 scale pages pack "
                "k/v scales against the GLOBAL kv-head count in their "
                "128-lane layout, which a lane-sharded pool would split "
                "mid-field — serve bf16/f32 pages or tp=1")
        devices = jax.devices()
        if len(devices) < tp * ep:
            raise ValueError(
                f"tp={tp} x ep={ep} needs {tp * ep} local devices, found "
                f"{len(devices)} (tests/tools force 8 virtual CPU "
                "devices via --xla_force_host_platform_device_count)")
        if tp > 1:
            n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
            if cfg.num_heads % tp or n_kv % tp:
                raise ValueError(
                    f"tp={tp} must divide num_heads={cfg.num_heads} and "
                    f"num_kv_heads={n_kv} (the KV pool shards by head)")
            inter = getattr(cfg, "intermediate_size", 0)
            if inter and inter % tp:
                raise ValueError(
                    f"tp={tp} must divide intermediate_size={inter}")
        if ep > 1:
            n_exp = getattr(cfg, "num_experts", 0)
            if not n_exp:
                raise ValueError(
                    f"ep={ep} on a dense model: expert parallelism "
                    "shards the stacked expert weights, which this "
                    "config does not have (num_experts=0) — serve an "
                    "MoE config or ep=1")
            if n_exp % ep:
                raise ValueError(
                    f"ep={ep} must divide num_experts={n_exp} (each "
                    "shard owns a contiguous block of experts)")
        if tp > 1 and ep > 1:
            # ep innermost: an expert all-to-all crosses the devices
            # that already exchange the Megatron psums' partners' data
            self.mesh = Mesh(
                np.asarray(devices[:tp * ep]).reshape(tp, ep),
                (self.AXIS, self.EP_AXIS))
        elif ep > 1:
            self.mesh = Mesh(np.asarray(devices[:ep]), (self.EP_AXIS,))
        else:
            self.mesh = Mesh(np.asarray(devices[:tp]), (self.AXIS,))
        self.param_specs = self._infer_param_specs()

    def _infer_param_specs(self) -> List:
        """One PartitionSpec per entry of the engine's ``_swap`` list
        (named_parameters then named_buffers, the order the compiled
        programs receive them in). Column/row assignment follows the
        owning layer's name; everything else replicates."""
        from jax.sharding import PartitionSpec as P

        model, tp = self.engine.model, self.tp
        specs: List = []
        for name, t in model.named_parameters():
            specs.append(self._spec_for(name, t.shape, P))
        for name, b in model.named_buffers():
            if b is not None:
                specs.append(P())
        return specs

    def _spec_for(self, name: str, shape, P):
        parts = name.split(".")
        layer = parts[-2] if len(parts) >= 2 else ""
        leaf = parts[-1]
        if leaf in _EXPERT_LEAVES:
            if self.ep == 1:
                return P()
            if shape[0] % self.ep:
                raise ValueError(
                    f"{name}: expert dim {shape[0]} not divisible by "
                    f"ep={self.ep}")
            return P(self.EP_AXIS, None, None)
        if self.tp == 1:
            return P()  # ep-only mesh: dense weights replicate
        if "qkv_proj" in name:
            raise NotImplementedError(
                "tp > 1 over a packed-QKV projection (GPT's [H, 3H] "
                "weight interleaves q/k/v per head in a layout a "
                "contiguous column shard would split wrongly) — serve a "
                "model family with separate q/k/v projections (LLaMA) "
                "or tp=1")
        if layer in _COL_LAYERS:
            if leaf == "weight":
                if shape[-1] % self.tp:
                    raise ValueError(
                        f"{name}: output dim {shape[-1]} not divisible "
                        f"by tp={self.tp}")
                return P(None, self.AXIS)
            return P(self.AXIS)  # column-parallel bias shards with cols
        if layer in _ROW_LAYERS:
            if leaf != "weight":
                raise NotImplementedError(
                    f"{name}: a row-parallel projection with a bias "
                    "would double-count it through the psum — bias-free "
                    "row layers only (the llama convention)")
            if shape[0] % self.tp:
                raise ValueError(
                    f"{name}: input dim {shape[0]} not divisible by "
                    f"tp={self.tp}")
            return P(self.AXIS, None)
        return P()

    @property
    def sharded(self) -> bool:
        return self.mesh is not None

    @property
    def page_spec(self):
        from jax.sharding import PartitionSpec as P

        # pages shard by KV-head lane over tp only; an ep-only mesh
        # keeps the pool replicated (every shard runs full attention)
        return P(None, None, self.AXIS) if self.tp > 1 else P()

    # -------------------------------------------------------- placement
    def place_params(self, arrays: List) -> List:
        """Pre-place the weight arrays on the mesh with their specs ONCE
        (engine init) so dispatches never re-shard them."""
        if not self.sharded:
            return list(arrays)
        from jax.sharding import NamedSharding

        return [jax.device_put(a, NamedSharding(self.mesh, s))
                for a, s in zip(arrays, self.param_specs)]

    def place_pages(self, arrays: List) -> List:
        """Shard page buffers across the TP axis (KV-head lanes). Used
        by the cache-coordinator at construction AND by pool reset after
        a step fault — donated-dead buffers rebuild per-shard, never as
        a replicated host array (ISSUE 11 satellite)."""
        if not self.sharded:
            return list(arrays)
        from jax.sharding import NamedSharding

        sh = NamedSharding(self.mesh, self.page_spec)
        return [jax.device_put(a, sh) for a in arrays]

    # ------------------------------------------------- host-tier copies
    # The KV tier's demote/promote dispatches (ISSUE 15). Both are tiny
    # jitted page-axis gathers/scatters in the _copy_pages idiom: the
    # page axis is unsharded, so at tp>1 GSPMD runs them over the
    # lane-sharded pool without a reshard — capture's outputs carry the
    # lane sharding (device_get in the spill worker assembles the GLOBAL
    # logical page for the host slab) and restore's donated outputs keep
    # the pool's NamedSharding, so a tp=N demote/promote round trip
    # preserves both bytes and layout. Neither ever blocks the engine
    # thread: capture hands back device handles (the worker does the
    # one synchronous device->host fetch), restore is a donated async
    # dispatch whose host->device payload transfer rides the dispatch.
    # Both take a PADDED page-index vector (pow2, pad slot 0 = the trash
    # page, the same convention every padded program row uses), so one
    # dispatch moves a whole demotion/promotion wave and the compile
    # cache stays one program per pow2 width.
    @property
    def capture_pages(self):
        fn = getattr(self, "_capture_fn", None)
        if fn is None:
            import jax

            def _capture(pages_flat, idx):
                return [b[idx] for b in pages_flat]

            fn = self._capture_fn = jax.jit(_capture)
        return fn

    @property
    def restore_pages(self):
        fn = getattr(self, "_restore_fn", None)
        if fn is None:
            import jax

            def _restore(pages_flat, idx, payload):
                return [b.at[idx].set(x)
                        for b, x in zip(pages_flat, payload)]

            fn = self._restore_fn = jax.jit(_restore, donate_argnums=0)
        return fn

    # ------------------------------------------------------ weight audit
    def fetch_param_slice(self, i: int, start: int,
                          stop: Optional[int]) -> np.ndarray:
        """Host copy of elements ``[start, stop)`` (row-major flat
        order; ``stop=None`` = whole tensor) of PLACED parameter ``i`` —
        the integrity sentinel's audit probe (ISSUE 14). TP-aware the
        same way the dispatches are: ``_params[i]`` carries its
        ``NamedSharding``, so the eager ravel+slice runs under GSPMD
        over the column/row shards and ``device_get`` assembles the
        GLOBAL logical values. The digest baseline is therefore
        layout-independent — a bit flipped in ANY shard's HBM lands in
        the fetched window's bytes regardless of which device holds it,
        and a tp=1 engine fetches the exact same values."""
        p = self.engine._params[i]
        flat = jnp.ravel(p)
        if start or stop is not None:
            flat = flat[int(start):(None if stop is None else int(stop))]
        return np.asarray(jax.device_get(flat))

    # ------------------------------------------------------- local view
    @contextlib.contextmanager
    def local_view(self, strip_collectives: bool = False):
        """Arm the model for a PER-SHARD trace: attention modules see the
        LOCAL head counts (global // tp) and row-parallel layers get
        their ``_tp_axis`` set so the forward inserts the Megatron g
        psums. A no-op at tp=1. ``strip_collectives`` keeps the sharded
        weights but skips the psums — the collective-stripped timing
        twin ``tools/multichip.py`` measures comm against (its outputs
        are partial sums, meaningful for wall-clock only)."""
        if not self.sharded:
            yield
            return
        tp = self.tp
        axis = None if strip_collectives else self.AXIS
        patched = []  # (obj, attr, old)

        def patch(obj, attr, new):
            patched.append((obj, attr, getattr(obj, attr, None),
                            hasattr(obj, attr)))
            setattr(obj, attr, new)

        for lyr in self.engine.model.sublayers(include_self=True):
            if hasattr(lyr, "router") and hasattr(lyr, "experts_gate"):
                # MoE: the all_to_all/all_gather pair is STRUCTURAL (a
                # shard only holds its expert block), so it stays armed
                # even under strip_collectives
                patch(lyr, "_ep_axis",
                      self.EP_AXIS if self.ep > 1 else None)
            elif tp > 1 and hasattr(lyr, "o_proj") \
                    and hasattr(lyr, "num_heads"):
                patch(lyr, "num_heads", lyr.num_heads // tp)
                if hasattr(lyr, "num_kv_heads"):
                    patch(lyr, "num_kv_heads", lyr.num_kv_heads // tp)
                patch(lyr, "_tp_axis", axis)
            elif tp > 1 and hasattr(lyr, "down_proj") \
                    and hasattr(lyr, "gate_proj"):
                patch(lyr, "_tp_axis", axis)
        try:
            yield
        finally:
            for obj, attr, old, existed in reversed(patched):
                if existed:
                    setattr(obj, attr, old)
                else:
                    delattr(obj, attr)

    # --------------------------------------------------------- wrapping
    def shard(self, raw, n_rest: int, out_desc: Tuple[str, ...],
              strip_collectives: bool = False):
        """shard_map-wrap a raw engine program (UNJITTED — the analyze
        registry traces this directly). ``raw(params, pages_flat,
        *rest)`` with ``n_rest`` trailing replicated operands;
        ``out_desc`` names each element of the return tuple: ``"r"``
        (replicated) or ``"pages"`` (the sharded pages_flat list)."""
        from jax.sharding import PartitionSpec as P

        from ..distributed.jax_compat import shard_map

        n_pages = 2 * self.engine.cfg.num_layers
        pg = [self.page_spec] * n_pages
        in_specs = (self.param_specs, pg) + (P(),) * n_rest
        out_specs = tuple(pg if d == "pages" else P() for d in out_desc)

        def body(params, pages_flat, *rest):
            with self.local_view(strip_collectives=strip_collectives):
                return raw(params, pages_flat, *rest)

        body.__name__ = getattr(raw, "__name__", "sharded_step")
        return shard_map(body, self.mesh, in_specs=in_specs,
                         out_specs=out_specs, check=False)

    def wrap(self, raw, n_rest: int, out_desc: Tuple[str, ...],
             donate=(1,)):
        """jit (tp=1) or jit∘shard_map (tp>1) a raw program, donating
        the page buffers either way."""
        fn = raw if not self.sharded else self.shard(raw, n_rest, out_desc)
        return functools.partial(jax.jit, donate_argnums=donate)(fn)

    # ------------------------------------------------- program builders
    # Raw builders live beside the engine (make_mixed_step_fn, the
    # closures below); the runner is where they meet the mesh. Each
    # get_* caches per shape key exactly as the monolithic engine did.
    # MoE engines grow ONE trailing replicated output per program (the
    # router-stats vector; replicated routing computes it identically
    # on every shard) — verify stays stats-free (tap unarmed there).
    @property
    def _moe_extra(self) -> Tuple[str, ...]:
        return ("r",) if getattr(self.engine, "_moe_stats_n", 0) else ()

    def get_decode(self, nb: int, k: int, sampling: bool):
        key = (nb, k, sampling)
        fn = self.decode_fns.get(key)
        if fn is None:
            eng = self.engine
            if eng._m is not None:
                eng._m.compiled.labels(kind="decode").inc()
            raw = eng._make_decode_raw(k, sampling)
            fn = self.wrap(raw, n_rest=5,
                           out_desc=("r", "pages", "r", "r", "r")
                           + self._moe_extra)
            self.decode_fns[key] = fn
        return fn

    def get_prefill(self, bucket, sampling: bool, suffix: bool = False):
        key = (bucket, sampling, suffix)
        fn = self.prefill_fns.get(key)
        if fn is None:
            eng = self.engine
            if eng._m is not None:
                eng._m.compiled.labels(kind="prefill").inc()
            raw = eng._make_prefill_raw(sampling, suffix)
            fn = self.wrap(raw, n_rest=6,
                           out_desc=("r", "r", "r", "pages")
                           + self._moe_extra)
            self.prefill_fns[key] = fn
        return fn

    def get_mixed(self, nb: int, sampling: bool):
        key = (nb, sampling)
        fn = self.mixed_fns.get(key)
        if fn is None:
            eng = self.engine
            if eng._m is not None:
                eng._m.compiled.labels(kind="mixed").inc()
            from .engine import make_mixed_step_fn

            raw = make_mixed_step_fn(eng, sampling)
            fn = self.wrap(raw, n_rest=7,
                           out_desc=("r", "r", "r", "pages")
                           + self._moe_extra)
            self.mixed_fns[key] = fn
        return fn

    def wrap_verify(self, raw):
        """Spec-decode verify program (built by spec/verifier.py; the
        SpecDecoder caches per sampling flag)."""
        return self.wrap(raw, n_rest=7,
                         out_desc=("r", "r", "r", "r", "r", "pages"))

    # ----------------------------------------------------- traceability
    def traceable(self, kind: str, sampling: bool = False, k: int = 1,
                  strip_collectives: bool = False):
        """The UNJITTED program for static analysis and the multichip
        harness: shard_map-wrapped at tp>1, the raw python function at
        tp=1. ``kind`` in {"decode", "mixed", "prefill", "suffix"}."""
        eng = self.engine
        if kind == "decode":
            raw, n_rest = eng._make_decode_raw(k, sampling), 5
            out = ("r", "pages", "r", "r", "r") + self._moe_extra
        elif kind == "mixed":
            from .engine import make_mixed_step_fn

            raw, n_rest = make_mixed_step_fn(eng, sampling), 7
            out = ("r", "r", "r", "pages") + self._moe_extra
        elif kind in ("prefill", "suffix"):
            raw = eng._make_prefill_raw(sampling, kind == "suffix")
            n_rest, out = 6, ("r", "r", "r", "pages") + self._moe_extra
        else:
            raise ValueError(f"unknown program kind {kind!r}")
        if not self.sharded:
            return raw
        fn = self.shard(raw, n_rest, out,
                        strip_collectives=strip_collectives)
        fn.__name__ = f"tp_sharded_{kind}_step"
        return fn
