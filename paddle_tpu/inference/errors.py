"""Structured error taxonomy for the serving engine (ISSUE 6 tentpole).

The paged engine's original failure surface was a handful of raw
``RuntimeError``/``ValueError`` raises mid-``step()`` — one bad request
killed the whole batch. Production engines in the vLLM/Orca lineage treat
per-request fault isolation as table stakes, so every failure the serving
stack can produce now has a typed home here, split along the one axis that
matters operationally: *whose* fault is it, and therefore *what dies*.

* ``RequestError`` subtree — scoped to ONE request. The engine catches
  these (and anything unexpected raised while processing one request),
  moves that request to the terminal ``FAILED`` state with
  ``failure_reason`` set to the class's ``reason`` slug, frees its slot
  and pages, and keeps serving everything else. The ``reason`` slug is
  the label on ``paddle_tpu_request_failures_total{reason}``, so the
  taxonomy here IS the metrics schema — add a class, get a series.
* ``EngineFault`` — a whole-step fault (a compiled dispatch died, host
  bookkeeping is mid-commit). ``Engine.step()`` never re-raises it
  either: recovery requeues every active request (recompute policy — the
  prefix re-prefills, the PRNG key travels, generation resumes exactly)
  and the watchdog counts it toward graceful degradation
  (``paddle_tpu/inference/watchdog.py``).

Admission-time classes double-inherit ``ValueError`` so existing callers
(and tests) that catch ``ValueError`` on ``add_request`` keep working —
reject-at-submission predates the taxonomy, only its type got sharper.

Pure stdlib; importing this module must never pull in jax.
"""
from __future__ import annotations

from typing import Optional

__all__ = [
    "EngineError", "RequestError", "ValidationError", "AdmissionRejected",
    "QueueFull", "DeadlineExceeded", "CancelledError", "PoolExhausted",
    "NumericsError", "DrafterFault", "StepFault", "CallbackError",
    "RetriesExhausted", "IntegrityError", "EngineFault", "failure_reason",
]


class EngineError(Exception):
    """Base of every taxonomy error the serving stack raises.

    ``reason`` is the stable metrics slug
    (``paddle_tpu_request_failures_total{reason=...}``) and the value
    stored on ``Request.failure_reason`` — treat it like a rule ID:
    never rename, retire and mint instead.
    """

    reason = "engine"

    def __init__(self, message: str = "", rid: Optional[int] = None):
        super().__init__(message)
        self.rid = rid


class RequestError(EngineError):
    """A fault scoped to one request: the engine fails THAT request
    (terminal ``FAILED`` state carrying ``reason``) and the co-batched
    requests keep decoding, bit-identical to a fault-free run."""

    reason = "request"


class ValidationError(RequestError, ValueError):
    """The request is malformed at submission: empty prompt, token ids
    outside the vocab, non-integer ids, a non-positive budget, or a
    prompt that leaves no room to generate. Rejected at ``add_request``
    — it never enters the queue."""

    reason = "validation"


class AdmissionRejected(RequestError, ValueError):
    """The request can NEVER be served by this engine's geometry (needs
    more KV pages than the pool/table can hold). Rejected at
    ``add_request`` so the scheduler never spins waiting for pages that
    cannot exist."""

    reason = "admission_rejected"


class QueueFull(AdmissionRejected):
    """Backpressure: the bounded wait queue (``Engine(max_queue=...)``)
    is at capacity. Callers shed or retry later — the engine refuses to
    buffer unboundedly."""

    reason = "queue_full"


class DeadlineExceeded(RequestError):
    """The request's deadline/TTL elapsed (queued or mid-decode). The
    engine expires it at the next scheduling step."""

    reason = "deadline"


class CancelledError(RequestError):
    """Host-side ``Engine.cancel(request_id)`` hit the request before it
    finished."""

    reason = "cancelled"


class PoolExhausted(RequestError):
    """KV page pool pressure this request cannot survive: it is alone in
    the batch (nobody left to preempt) and still cannot get pages, or
    its sequence outgrew the per-sequence page table."""

    reason = "pool_exhausted"


class NumericsError(RequestError):
    """The in-program NaN/inf logit guard flagged this request's row —
    its tokens are garbage (argmax over NaN) and are discarded rather
    than streamed."""

    reason = "nan_logits"


class DrafterFault(RequestError):
    """The speculative-decoding drafter raised (or was fault-injected).
    The step falls back to drafting nothing — a zero-draft verify is
    exactly a vanilla decode step, so greedy output is unchanged — and
    the watchdog counts the fault toward spec→vanilla degradation."""

    reason = "drafter"


class StepFault(RequestError):
    """An unexpected exception while processing ONE request's harvest /
    bookkeeping. Wraps the original as ``__cause__``."""

    reason = "step_fault"


class CallbackError(StepFault):
    """The request's ``on_token`` streaming callback raised. The
    callback belongs to the caller; its failure fails the request, never
    the batch."""

    reason = "callback"


class RetriesExhausted(RequestError):
    """The request was preempted/requeued more than ``max_retries``
    times. The bound converts allocator livelock (two big requests
    endlessly evicting each other) into one bounded, attributable
    failure."""

    reason = "retries_exhausted"


class IntegrityError(RequestError):
    """Silent data corruption detected by the integrity layer (ISSUE 14):
    a checkpoint file's content digest no longer matches its metadata, a
    KV page's checksum changed between registration and splice, a weight
    shard's audit digest drifted from the load-time baseline, or a
    shadow-recomputed token disagrees with the one the compiled path
    delivered. The one taxonomy class whose *cause* is never the
    request: the hardware (or a kernel) lied, and the containment ladder
    decides the blast radius — cache miss (KV), request requeue/FAILED
    (active KV / shadow divergence), replica quarantine (weights), or
    restore fallback to an older step (checkpoint).

    Handling discipline is enforced by tpulint TPL1002: an ``except``
    that can absorb this class under ``paddle_tpu/{inference,
    distributed,serving}/`` must re-raise or route into the taxonomy —
    a swallowed integrity signal is exactly the silent corruption this
    layer exists to surface."""

    reason = "integrity"


class EngineFault(EngineError):
    """A whole-step fault: the compiled dispatch (or the step's host
    spine) raised. Recovery is engine-level — requeue-all + pool reset —
    not per-request."""

    reason = "engine"


def failure_reason(exc: BaseException) -> str:
    """The metrics/``Request.failure_reason`` slug for any exception:
    the taxonomy class's ``reason``, or ``"unhandled"`` for foreign
    exception types (which the engine wraps in ``StepFault`` anyway)."""
    return getattr(exc, "reason", None) or "unhandled"
