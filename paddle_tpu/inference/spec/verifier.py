"""The batched spec-decode verifier (ISSUE 5 tentpole, part 2).

One compiled program scores ALL k draft positions in ONE forward through
the existing paged decode path: the input row is ``[last_tok, d1..dk]``,
the ``PagedCacheState(verify=True)`` flag routes every attention layer
through ``paged_state_verify`` (append k+1 rows at [len, len+k+1), attend
each position over cache + causal prefix), and acceptance runs in the
same program — so a verify step costs exactly one dispatch + one fetch,
like a vanilla decode chunk.

Cache rollback happens INSIDE the program: the returned ``new_lengths``
is ``len + 1 + n_accepted`` (the accepted prefix), not ``len + k + 1``
(what was physically written). Rejected rows become dead data past
``lengths`` — the same data-only-exists-up-to-``lengths`` invariant the
engine's trash page relies on — and the host returns their headroom
pages via ``Engine._trim_pages`` at harvest.

``make_verify_fn`` returns the UNJITTED python function (the engine
wraps it with ``jax.jit(donate_argnums=(1,))`` so the page buffers reuse
in place); the tpucheck registry (``tools/analyze_tpu.py`` entry
``spec_verify_step``) traces the same raw function, so ``make analyze``
sweeps the real serving program for liveness/collective/donation/cost
findings.
"""
from __future__ import annotations

import jax.numpy as jnp

from .acceptance import accept_tokens

__all__ = ["make_verify_fn"]


def make_verify_fn(engine, sampling):
    """Build the raw verify step for ``engine``. Shapes (batch bucket nb,
    draft width k) are inferred from the arguments, so one function per
    ``sampling`` flag serves every (nb, k) jit specialization."""
    model = engine.model

    def spec_verify_step(params, pages_flat, tables, lengths, last_tok,
                         drafts, draft_len, temps, keys):
        from ...framework.tensor import Tensor, pause_tape
        from ...jit import swapped_tensors

        with swapped_tensors(engine._swap, params), pause_tape():
            ids = jnp.concatenate([last_tok[:, None], drafts], axis=1)
            states = engine._states_from(pages_flat, tables, lengths,
                                         verify=True)
            logits, new_states = model.forward(Tensor._wrap(ids),
                                               caches=states)
            lg = (logits._data if isinstance(logits, Tensor)
                  else logits).astype(jnp.float32)
            # NaN/inf logit guard (ISSUE 6): any non-finite position in a
            # row's k+1 scored logits poisons acceptance for that row —
            # flag it so the host fails THAT request, not the batch
            bad = ~jnp.all(jnp.isfinite(lg), axis=(1, 2))
            toks, n_emit, new_keys = accept_tokens(
                lg, drafts, draft_len, temps, keys,
                top_k=engine.top_k, sampling=sampling)
            # roll back to the accepted prefix: base + (last_tok + accepted
            # drafts) rows are live, rejected rows are dead data the next
            # append overwrites. Idle/pad rows (length 0) stay 0.
            active = lengths > 0
            cap = tables.shape[1] * engine.page_size
            new_lengths = jnp.where(
                active, jnp.minimum(lengths + n_emit, cap), lengths)
            return (toks, n_emit, new_lengths, new_keys, bad,
                    engine._pages_of(new_states))

    return spec_verify_step
