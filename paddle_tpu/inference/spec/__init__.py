"""paddle_tpu.inference.spec — speculative decoding for the paged engine.

ISSUE 5 tentpole: decode is memory-bound (the PR 4 roofline pass
confirmed each step streams ALL weight bytes to emit one token per
sequence), so the step's cost is nearly flat in how many positions it
scores. Speculative decoding amortizes the weight stream over k+1
positions per step: a cheap **drafter** proposes k tokens, one batched
**verifier** forward through the existing paged decode path scores every
position at once, and an **acceptance** rule keeps the usable prefix —
token-exact argmax matching for greedy requests (output provably
identical to vanilla decode), distribution-preserving rejection sampling
for temperature > 0. Rejected rows roll back through the engine's page
allocator (``_trim_pages``), so preemption/eviction invariants hold.

Wiring: ``Engine(model, spec="ngram"|"draft", spec_k=4,
draft_model=...)`` — see ``Engine._spec_step`` for the scheduling loop
and README "Speculative decoding" for semantics and flags.
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Set, Tuple

from .acceptance import accept_tokens
from .controller import AdaptiveDraftController
from .drafter import DraftModelDrafter, NgramDrafter
from .verifier import make_verify_fn

__all__ = ["SpecDecoder", "NgramDrafter", "DraftModelDrafter",
           "AdaptiveDraftController", "accept_tokens", "make_verify_fn"]


class _SpecMetrics:
    """Spec observability bundle (ISSUE 5 satellite): registered only
    when spec decoding is ON, so vanilla engines keep their scrape
    unchanged. All recording is host code between dispatches."""

    def __init__(self, drafter_name: str):
        from ...observability import SIZE_BUCKETS, counter, histogram

        self.proposed = counter(
            "paddle_tpu_spec_proposed_total",
            "draft tokens proposed to the verifier",
            labelnames=("drafter",)).labels(drafter=drafter_name)
        self.accepted = counter(
            "paddle_tpu_spec_accepted_total",
            "draft tokens accepted by the verifier",
            labelnames=("drafter",)).labels(drafter=drafter_name)
        self.draft_len = histogram(
            "paddle_tpu_spec_draft_len",
            "drafts proposed per request per verify step",
            buckets=SIZE_BUCKETS)
        self.tokens_per_step = histogram(
            "paddle_tpu_spec_tokens_per_verify_step",
            "tokens landed per request per verify step (1 + accepted)",
            buckets=SIZE_BUCKETS)
        self.drafter_faults = counter(
            "paddle_tpu_spec_drafter_faults_total",
            "drafter proposals that raised (step fell back to zero "
            "drafts — vanilla-equivalent)",
            labelnames=("drafter",)).labels(drafter=drafter_name)


class SpecDecoder:
    """Engine-side spec-decode state: the drafter, the per-request
    adaptive controller, the compiled verify programs, and the rolling
    stats bench.py / the Prometheus scrape report."""

    def __init__(self, engine, mode: str, k: int = 4, draft_model=None,
                 max_ngram: int = 3, min_ngram: int = 1):
        if mode == "ngram":
            self.drafter = NgramDrafter(max_ngram=max_ngram,
                                        min_ngram=min_ngram)
        elif mode == "draft":
            if draft_model is None:
                raise ValueError(
                    'spec="draft" needs draft_model=<small causal LM '
                    "sharing the target's vocab>")
            self.drafter = DraftModelDrafter(draft_model, engine)
        else:
            raise ValueError(
                f"spec={mode!r}: expected 'ngram' or 'draft' (or "
                "None/'off' for vanilla decode)")
        # the verify block (k+1 rows) must fit the chunk_size headroom
        # add_request reserves below max_position, so positions never
        # outrun the page tables even at a request's budget edge
        self.k = max(1, min(int(k), engine.chunk_size))
        self.engine = engine
        self.controller = AdaptiveDraftController(self.k)
        self._verify_raw: Dict[bool, object] = {}
        self._verify_fns: Dict[bool, object] = {}
        self._seen_shapes: Set[Tuple[int, int, bool]] = set()
        self._m: Optional[_SpecMetrics] = (
            _SpecMetrics(self.drafter.name)
            if engine._m is not None else None)
        # rolling totals for bench.py and the adaptive-depth export
        self.verify_steps = 0      # verify dispatches
        self.request_steps = 0     # per-request verify rows harvested
        self.tokens_landed = 0     # tokens delivered via spec steps
        self.drafts_proposed = 0
        self.drafts_accepted = 0
        self.drafter_faults = 0    # proposals that raised (ISSUE 6)
        self.last_drafter_fault = None
        self.wall_seconds = 0.0    # _spec_step wall covered by the above

    # ---------------------------------------------------------- programs
    def get_verify(self, nb: int, sampling: bool):
        fn = self._verify_fns.get(sampling)
        if fn is None:
            raw = make_verify_fn(self.engine, sampling)
            # the model-runner wraps (jit, plus shard_map at tp>1 —
            # verify rides the same sharded weights/pool as decode)
            fn = self.engine.runner.wrap_verify(raw)
            self._verify_fns[sampling] = fn
        shape = (nb, self.k, sampling)
        if shape not in self._seen_shapes:
            self._seen_shapes.add(shape)
            if self.engine._m is not None:
                self.engine._m.compiled.labels(kind="verify").inc()
        return fn

    # ------------------------------------------------------- accounting
    def note(self, req, proposed: int, accepted: int, landed: int):
        """Per-request post-harvest bookkeeping for one verify row."""
        self.controller.update(req, proposed, accepted)
        self.request_steps += 1
        self.tokens_landed += landed
        self.drafts_proposed += proposed
        self.drafts_accepted += min(accepted, proposed)
        if self._m is not None:
            if proposed:
                self._m.proposed.inc(proposed)
                self._m.accepted.inc(min(accepted, proposed))
            self._m.draft_len.observe(proposed)
            self._m.tokens_per_step.observe(landed)

    def observe_step(self, wall: float):
        self.verify_steps += 1
        self.wall_seconds += wall

    def note_drafter_fault(self, exc: BaseException):
        """Drafter raised (ISSUE 6): count it and reset the drafter's
        private cache so the next proposal re-syncs every slot from the
        request's host-side token history — the slot-reconciliation-
        after-failure contract. ``reset()`` never raises by contract."""
        self.drafter_faults += 1
        self.last_drafter_fault = exc
        self.drafter.reset()
        if self._m is not None:
            self._m.drafter_faults.inc()

    def stats(self) -> dict:
        """Rolling summary: mean landed tokens per request-row per verify
        step, draft acceptance rate, measured spec ms/token."""
        return {
            "drafter": self.drafter.name,
            "k": self.k,
            "verify_steps": self.verify_steps,
            "tokens_landed": self.tokens_landed,
            "accept_per_step": (
                self.tokens_landed / self.request_steps
                if self.request_steps else 0.0),
            "accept_rate": (
                self.drafts_accepted / self.drafts_proposed
                if self.drafts_proposed else 0.0),
            "drafter_faults": self.drafter_faults,
            "spec_ms_per_token": (
                1e3 * self.wall_seconds / self.tokens_landed
                if self.tokens_landed else 0.0),
        }
