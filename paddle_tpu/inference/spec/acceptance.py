"""Draft-token acceptance for speculative decoding (ISSUE 5).

Traced device code: runs INSIDE the engine's compiled verify program, so
the whole accept/resample decision costs zero extra host round trips and
the per-request PRNG key state threads through exactly like the vanilla
``Engine._select_token`` path (keys survive preemption with the request).

Semantics (Leviathan et al. 2023, specialized to point-mass proposals —
both shipped drafters propose deterministic tokens, so q(x) = δ_d):

* **Greedy rows** (``temperature == 0``): accept the longest prefix of
  drafts that token-exactly matches the target argmax chain, then emit
  the argmax at the first mismatch (the "correction" token). The emitted
  stream is the vanilla greedy chain BY CONSTRUCTION — drafter quality
  only changes how many tokens land per step, never which tokens. Key
  state is untouched (greedy requests stay key-independent, matching
  ``_select_token``).
* **Sampled rows** (``temperature > 0``): accept draft ``d`` at position
  ``j`` with probability ``p_j(d)`` (= min(1, p/q) for q = δ_d); on the
  first rejection sample from the residual ``norm(max(p - q, 0))`` — p
  with the rejected token removed and renormalized. If every draft is
  accepted (or none was proposed), the bonus token samples from p
  directly. This preserves the target distribution exactly, position by
  position — the distribution test in tests/test_spec_decode.py checks
  the emitted-token marginal against target softmax empirically.

Top-k filtering and temperature scaling replicate ``_select_token``'s
order (filter raw logits, then scale), so spec and vanilla sampling draw
from identical per-position distributions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["accept_tokens"]


def accept_tokens(logits, drafts, draft_len, temps, keys, top_k=None,
                  sampling=True):
    """Score a verify block and pick the accepted tokens.

    logits    [B, k+1, V] f32 — target logits at the k+1 verify positions
              (position j conditions on the context through input row j)
    drafts    [B, k] i32     — proposed draft tokens
    draft_len [B] i32        — valid drafts per row (rest is padding)
    temps     [B] f32        — 0 = greedy
    keys      [B, 2] u32     — live per-request PRNG keys
    sampling  static         — False compiles the greedy-only program
                               without any RNG machinery (the common
                               serving case, mirroring ``_get_decode``)

    Returns ``(toks [B, k+1] i32, n_emit [B] i32, new_keys [B, 2])``:
    ``toks[b, :n_emit[b]]`` is the accepted draft prefix followed by one
    bonus/correction token; key state only burns for sampled rows.
    """
    b, m, v = logits.shape
    k = m - 1
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1]
        logits = jnp.where(logits >= kth[..., None], logits, -jnp.inf)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, m]
    j = jnp.arange(k, dtype=jnp.int32)[None]
    valid = j < draft_len[:, None]  # [B, k]
    accept_greedy = valid & (drafts == greedy[:, :k])

    if not sampling:
        accept = accept_greedy
        new_keys = keys
        n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                        axis=1)
        bonus = jnp.take_along_axis(greedy, n_acc[:, None], axis=1)[:, 0]
    else:
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None, None]
        probs = jax.nn.softmax(scaled, axis=-1)  # [B, m, V]
        # burn k+2 subkeys per row: k acceptance uniforms, 1 categorical
        # for the bonus/residual draw, 1 carried key — a FIXED schedule
        # (independent of draft_len/acceptance), so a request's key
        # stream depends only on how many verify steps it has lived
        # through, never on batch composition
        splits = jax.vmap(lambda key: jax.random.split(key, k + 2))(keys)
        new_keys = splits[:, 0]
        u = jax.vmap(lambda ks: jax.vmap(jax.random.uniform)(ks))(
            splits[:, 1:k + 1])  # [B, k] in [0, 1)
        p_draft = jnp.take_along_axis(
            probs[:, :k], drafts[..., None], axis=-1)[..., 0]  # [B, k]
        accept_sampled = valid & (u < p_draft)
        accept = jnp.where((temps > 0.0)[:, None], accept_sampled,
                           accept_greedy)
        n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                        axis=1)  # [B] in [0, k]
        # bonus token from position n_acc: residual (rejected draft
        # removed) when a proposal was rejected there, plain target
        # sampling when drafts simply ran out
        final_scaled = jnp.take_along_axis(
            scaled, n_acc[:, None, None], axis=1)[:, 0]  # [B, V]
        rejected = n_acc < draft_len
        rej_tok = jnp.take_along_axis(
            drafts, jnp.clip(n_acc, 0, k - 1)[:, None], axis=1)[:, 0]
        drop = ((jnp.arange(v, dtype=jnp.int32)[None] == rej_tok[:, None])
                & rejected[:, None])
        final_scaled = jnp.where(drop, -jnp.inf, final_scaled)
        sampled_bonus = jax.vmap(jax.random.categorical)(
            splits[:, k + 1], final_scaled).astype(jnp.int32)
        final_greedy = jnp.take_along_axis(
            greedy, n_acc[:, None], axis=1)[:, 0]
        bonus = jnp.where(temps > 0.0, sampled_bonus, final_greedy)
        new_keys = jnp.where((temps > 0.0)[:, None], new_keys, keys)

    # assemble [accepted draft prefix, bonus, 0 padding]
    pos = jnp.arange(m, dtype=jnp.int32)[None]
    draft_pad = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
    toks = jnp.where(pos < n_acc[:, None], draft_pad,
                     jnp.where(pos == n_acc[:, None], bonus[:, None], 0))
    return toks.astype(jnp.int32), (n_acc + 1).astype(jnp.int32), new_keys
