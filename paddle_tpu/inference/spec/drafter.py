"""Pluggable drafters for speculative decoding (ISSUE 5 tentpole, part 1).

Two implementations of the same contract — ``propose(engine, slots,
reqs, want, k)`` returns ``(drafts, dlen)`` where ``drafts`` is a
``[pow2ceil(n), k]`` int32 array (host numpy or device jnp — the verify
program takes either) aligned with the sorted-slot batch order and
``dlen[i] <= k`` counts the valid proposals per row:

* ``NgramDrafter`` — model-free prompt lookup (PLD / n-gram): match the
  request's most recent n-gram earlier in its own prompt+generation
  history and propose the tokens that followed. Pure host numpy, zero
  extra dispatches, works on any model including the tiny test configs —
  and is remarkably effective on repetitive continuations (exactly what
  memory-bound decode serves a lot of: code, templated text, and — on
  the untrained tiny models — the greedy repetition loops the bench
  workload exploits).
* ``DraftModelDrafter`` — a small causal LM drafts k tokens by greedy
  chained decode over ITS OWN paged KV pool (same page/table machinery
  as the engine, one jitted k-step scan per proposal). The draft cache
  tracks the target's accepted history by construction: before each
  proposal, ``_sync`` reconciles the per-slot draft cache against the
  request's host-side token history — rolling back rejected draft rows,
  appending catch-up tokens through a verify-mode forward (full-context
  attention, logits discarded — prefill-window attention would compute
  WRONG deep-layer k/v over a non-empty cache), and re-prefilling from
  scratch after preemption or slot reuse. No callbacks needed: the sync
  derives everything from ``(rid, cached_len)`` vs the request state.
"""
from __future__ import annotations

import functools
from typing import Dict, List

import numpy as np

__all__ = ["NgramDrafter", "DraftModelDrafter"]


def _pow2ceil(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _history(req) -> np.ndarray:
    """The request's full token history: prompt + everything generated
    (INCLUDING the current last token — drafting continues from it)."""
    if req.tokens:
        return np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
    return np.asarray(req.prompt, np.int32)


class NgramDrafter:
    """Prompt-lookup drafting: propose the continuation of the latest
    earlier occurrence of the current tail n-gram, longest n first."""

    name = "ngram"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def _lookup(self, ctx: np.ndarray, want: int) -> np.ndarray:
        L = ctx.size
        if want <= 0 or L < self.min_ngram + 1:
            return np.zeros((0,), np.int32)
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            pat = ctx[L - n:]
            windows = np.lib.stride_tricks.sliding_window_view(ctx, n)
            hits = np.nonzero((windows == pat).all(axis=1))[0]
            # earlier occurrences only (the tail n-gram matches itself),
            # with at least one continuation token
            hits = hits[hits <= L - n - 1]
            if not hits.size:
                continue
            # prefer the LATEST hit whose continuation window is FULL:
            # in a repetition run the latest hit sits flush against the
            # end of context and would truncate the proposal to a token
            # or two — exactly the regime where a full-width proposal
            # all lands. Fall back to the latest hit otherwise.
            full = hits[hits <= L - n - want]
            j = int(full[-1] if full.size else hits[-1]) + n
            return ctx[j:j + want].astype(np.int32)
        return np.zeros((0,), np.int32)

    def propose(self, engine, slots, reqs, want, k):
        n = len(reqs)
        drafts = np.zeros((_pow2ceil(max(n, 1)), k), np.int32)
        dlen = np.zeros((n,), np.int32)
        for i, req in enumerate(reqs):
            got = self._lookup(_history(req), min(int(want[i]), k))
            drafts[i, :got.size] = got
            dlen[i] = got.size
        return drafts, dlen

    def release(self, slot):  # stateless
        pass

    def reset(self):  # stateless; part of the drafter fault contract —
        pass          # reset() must never raise (engine calls it bare)


class DraftModelDrafter:
    """Draft with a small causal LM over its own paged KV pool."""

    name = "draft"

    def __init__(self, model, engine):
        cfg = model.config
        if cfg.vocab_size != engine.cfg.vocab_size:
            raise ValueError(
                f"draft model vocab ({cfg.vocab_size}) must match the "
                f"target's ({engine.cfg.vocab_size})")
        self.model = model
        self.cfg = cfg
        self.page_size = engine.page_size
        self.num_pages = engine.num_pages
        self.max_pages_per_seq = min(engine.max_pages_per_seq,
                                     cfg.max_position // engine.page_size)
        self.dtype = engine.dtype
        import jax.numpy as jnp

        n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
        shape = (self.num_pages, self.page_size, n_kv * cfg.head_dim)
        self.k_pages = [jnp.zeros(shape, self.dtype)
                        for _ in range(cfg.num_layers)]
        self.v_pages = [jnp.zeros(shape, self.dtype)
                        for _ in range(cfg.num_layers)]
        # host allocator mirrors the engine's: page 0 is the trash page
        self.tables = np.zeros((engine.max_slots, self.max_pages_per_seq),
                               np.int32)
        self.lengths = np.zeros((engine.max_slots,), np.int32)
        self._free_pages = list(range(self.num_pages - 1, 0, -1))
        # prefix caching (ISSUE 8): the drafter runs the engine's
        # refcount+cache machinery over its OWN pool (draft-model KV is
        # different content, so it needs its own index), enabled iff the
        # engine's cache is — a re-prefill after preemption/slot reuse
        # then splices cached draft pages instead of recomputing them
        self._page_ref = np.zeros((self.num_pages,), np.int32)
        if getattr(engine, "_pcache", None) is not None:
            from ..prefix_cache import PrefixCache

            self._pcache = PrefixCache(self.page_size)
        else:
            self._pcache = None
        self._slot_rid = np.full((engine.max_slots,), -1, np.int64)
        self._last = np.zeros((engine.max_slots,), np.int32)
        self._swap = [p for _, p in model.named_parameters()]
        self._swap += [b for _, b in model.named_buffers() if b is not None]
        self._params = [t._data for t in self._swap]
        self._propose_fns: Dict[int, object] = {}  # k -> jitted scan
        self._catchup_fn = None

    # ------------------------------------------------------- allocator
    def _pages_needed(self, length):
        return (int(length) + self.page_size - 1) // self.page_size

    def _alloc_page(self):
        """Free list first, then LRU-evict an idle cached draft page —
        the drafter twin of ``Engine._alloc_page``."""
        if self._free_pages:
            page = self._free_pages.pop()
        elif self._pcache is not None:
            page = self._pcache.evict_lru(self._page_ref)
            if page is None:
                return None
        else:
            return None
        self._page_ref[page] = 1
        return page

    def _release_page(self, page):
        page = int(page)
        if page <= 0:
            return
        ref = int(self._page_ref[page]) - 1
        assert ref >= 0, f"draft page {page} refcount went negative"
        self._page_ref[page] = ref
        if ref == 0 and not (self._pcache is not None
                             and self._pcache.contains_page(page)):
            self._free_pages.append(page)

    def _ensure_pages(self, slot, new_len) -> bool:
        need = min(self._pages_needed(new_len), self.max_pages_per_seq)
        have = int(np.count_nonzero(self.tables[slot]))
        taken: List[int] = []
        for i in range(have, need):
            page = self._alloc_page()
            if page is None:
                for j in range(have, have + len(taken)):
                    self.tables[slot, j] = 0
                for pg in reversed(taken):
                    self._release_page(pg)
                return False
            taken.append(page)
            self.tables[slot, i] = page
        return True

    def _trim_pages(self, slot, keep_len):
        need = self._pages_needed(keep_len)
        have = int(np.count_nonzero(self.tables[slot]))
        for i in range(have - 1, need - 1, -1):
            self._release_page(int(self.tables[slot, i]))
            self.tables[slot, i] = 0

    def release(self, slot):
        """Forget a slot (request finished / preempted / slot reused).
        Refcount-aware: cached draft pages stay resident at refcount 0."""
        for p in self.tables[slot]:
            if p:
                self._release_page(int(p))
        self.tables[slot, :] = 0
        self.lengths[slot] = 0
        self._slot_rid[slot] = -1

    def reset(self):
        """Drop ALL drafter state and rebuild the page buffers (ISSUE 6:
        slot reconciliation after a drafter fault or engine pool reset).
        Safe because ``_sync`` re-prefills any slot whose cache doesn't
        match the request's host-side history — which after this is
        every slot. Must never raise."""
        import jax.numpy as jnp

        n_kv = getattr(self.cfg, "num_kv_heads", self.cfg.num_heads)
        shape = (self.num_pages, self.page_size, n_kv * self.cfg.head_dim)
        self.k_pages = [jnp.zeros(shape, self.dtype)
                        for _ in range(self.cfg.num_layers)]
        self.v_pages = [jnp.zeros(shape, self.dtype)
                        for _ in range(self.cfg.num_layers)]
        self.tables[:] = 0
        self.lengths[:] = 0
        self._free_pages = list(range(self.num_pages - 1, 0, -1))
        # cached content died with the buffers: flush (stale-pointer
        # safety, same contract as Engine._reset_pool)
        self._page_ref[:] = 0
        if self._pcache is not None:
            self._pcache.clear()
        self._slot_rid[:] = -1

    # ------------------------------------------------------ jit bodies
    def _states_from(self, pages_flat, tables, lengths, verify=False):
        from ...ops.pallas.paged_attention import PagedCacheState

        L = self.cfg.num_layers
        return [PagedCacheState(pages_flat[i], pages_flat[L + i], None,
                                tables, lengths, self.page_size,
                                verify=verify)
                for i in range(L)]

    @staticmethod
    def _pages_of(states):
        return [st.k_pages for st in states] + [st.v_pages for st in states]

    def _pages_flat(self):
        return list(self.k_pages) + list(self.v_pages)

    def _set_pages(self, pages_flat):
        L = self.cfg.num_layers
        self.k_pages = list(pages_flat[:L])
        self.v_pages = list(pages_flat[L:2 * L])

    def _get_catchup(self):
        """Verify-mode forward that only WRITES: appends each row's delta
        tokens to the draft cache with full-context attention (correct
        deep-layer k/v) and discards the logits."""
        if self._catchup_fn is not None:
            return self._catchup_fn
        import jax
        import jax.numpy as jnp

        drafter, dmodel = self, self.model

        @functools.partial(jax.jit, donate_argnums=(1,))
        def draft_catchup(params, pages_flat, tables, lengths, ids, delta):
            from ...framework.tensor import Tensor, pause_tape
            from ...jit import swapped_tensors

            with swapped_tensors(drafter._swap, params), pause_tape():
                states = drafter._states_from(pages_flat, tables, lengths,
                                              verify=True)
                _, new_states = dmodel.forward(Tensor._wrap(ids),
                                               caches=states)
                # rows past each slot's true delta are garbage the next
                # write overwrites; lengths advances by delta only
                return (drafter._pages_of(new_states), lengths + delta)

        self._catchup_fn = draft_catchup
        return draft_catchup

    def _get_propose(self, k):
        """k greedy decode steps as ONE jitted scan (the draft-side twin
        of ``Engine._get_decode`` at chunk depth k)."""
        fn = self._propose_fns.get(k)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        drafter, dmodel = self, self.model

        @functools.partial(jax.jit, donate_argnums=(1,))
        def draft_propose(params, pages_flat, tables, lengths, last_tok):
            from ...framework.tensor import Tensor, pause_tape
            from ...jit import swapped_tensors

            with swapped_tensors(drafter._swap, params), pause_tape():
                def body(carry, _):
                    pages_flat, lengths, last = carry
                    states = drafter._states_from(pages_flat, tables,
                                                  lengths)
                    logits, new_states = dmodel.forward(
                        Tensor._wrap(last[:, None]), caches=states)
                    lg = (logits._data if isinstance(logits, Tensor)
                          else logits)
                    nxt = jnp.argmax(lg[:, -1].astype(jnp.float32),
                                     axis=-1).astype(jnp.int32)
                    return ((drafter._pages_of(new_states),
                             new_states[0].lengths, nxt), nxt)

                (pages_flat, lengths, _), toks = jax.lax.scan(
                    body, (pages_flat, lengths, last_tok), None, length=k)
            return jnp.swapaxes(toks, 0, 1), pages_flat, lengths

        self._propose_fns[k] = draft_propose
        return draft_propose

    # -------------------------------------------------------- proposal
    def _sync(self, slots, reqs):
        """Reconcile each slot's draft cache with the request's accepted
        history. Returns the catch-up rows [(slot, delta_tokens)]. The
        draft cache invariant matches the engine's: it holds the full
        context EXCEPT the current last token (whose k/v the next
        propose scan appends)."""
        rows = []
        for slot, req in zip(slots, reqs):
            hist = _history(req)
            expected = hist.size - 1  # everything but the last token
            if int(self._slot_rid[slot]) != req.rid:
                self.release(slot)
                self._slot_rid[slot] = req.rid
                if self._pcache is not None and expected > 0:
                    # re-prefill (admission / preemption / slot reuse)
                    # hits the draft-side prefix cache too (ISSUE 8):
                    # splice the cached block-aligned prefix so the
                    # catch-up forward only computes the uncached tail.
                    # matched is block-aligned and <= expected, so the
                    # catch-up/propose writes land past every shared page
                    pages, matched = self._pcache.lookup(hist[:expected])
                    for i, p in enumerate(pages):
                        self.tables[slot, i] = p
                        self._page_ref[p] += 1
                    self.lengths[slot] = matched
            cached = int(self.lengths[slot])
            if cached > expected:
                # roll back past-propose rows the verifier rejected
                self.lengths[slot] = expected
                self._trim_pages(slot, expected)
                cached = expected
            if cached < expected:
                rows.append((slot, hist[cached:expected]))
            self._last[slot] = hist[-1]
        return rows

    def propose(self, engine, slots, reqs, want, k):
        import jax
        import jax.numpy as jnp

        n = len(slots)
        nb = _pow2ceil(max(n, 1))
        dlen = np.asarray([min(int(w), k) for w in want], np.int32)
        sync_rows = self._sync(slots, reqs)
        # ---- catch-up wave (admission/preemption/bonus-token deltas) ----
        # A slot the draft pool can't grow is RELEASED outright (tables
        # zeroed → its propose-scan row writes to the trash page and
        # stays idle): proposing over a half-synced cache would leave
        # stale k/v behind the rollback watermark — silent corruption.
        degraded = set()
        rows = []
        for s, d in sync_rows:
            if self._ensure_pages(s, int(self.lengths[s]) + d.size):
                rows.append((s, d))
            else:
                self.release(s)
                degraded.add(s)
        if rows:
            width = _pow2ceil(max(d.size for _, d in rows))
            rb = _pow2ceil(len(rows))
            ids = np.zeros((rb, width), np.int32)
            tables_c = np.zeros((rb, self.max_pages_per_seq), np.int32)
            lengths_c = np.zeros((rb,), np.int32)
            delta_c = np.zeros((rb,), np.int32)
            for i, (s, d) in enumerate(rows):
                ids[i, :d.size] = d
                tables_c[i] = self.tables[s]
                lengths_c[i] = self.lengths[s]
                delta_c[i] = d.size
            pages, new_len = self._get_catchup()(
                self._params, self._pages_flat(), jnp.asarray(tables_c),
                jnp.asarray(lengths_c), jnp.asarray(ids),
                jnp.asarray(delta_c))
            self._set_pages(pages)
            for i, (s, _) in enumerate(rows):
                self.lengths[s] = int(lengths_c[i] + delta_c[i])
        if self._pcache is not None:
            # publish every synced slot's full draft-KV blocks (content-
            # addressed, so a future re-prefill of the same history — or
            # another request sharing the template — splices them)
            for s, req in zip(slots, reqs):
                if s in degraded:
                    continue
                hist = _history(req)
                full = int(self.lengths[s]) // self.page_size
                if full:
                    self._pcache.register(
                        hist[:full * self.page_size],
                        [int(self.tables[s, i]) for i in range(full)])
        # ---- propose scan: k greedy steps for the whole batch ----------
        for i, s in enumerate(slots):
            if s not in degraded and not self._ensure_pages(
                    s, int(self.lengths[s]) + k):
                self.release(s)
                degraded.add(s)
            if s in degraded:
                dlen[i] = 0  # draft pool pressure: degrade, don't stall
        tables_c = np.zeros((nb, self.max_pages_per_seq), np.int32)
        lengths_c = np.zeros((nb,), np.int32)
        last_c = np.zeros((nb,), np.int32)
        for i, s in enumerate(slots):
            tables_c[i] = self.tables[s]
            lengths_c[i] = self.lengths[s]
            last_c[i] = self._last[s]
        drafts, pages, new_len = self._get_propose(k)(
            self._params, self._pages_flat(), jnp.asarray(tables_c),
            jnp.asarray(lengths_c), jnp.asarray(last_c))
        self._set_pages(pages)
        new_len = np.asarray(jax.device_get(new_len))
        for i, s in enumerate(slots):
            self.lengths[s] = int(new_len[i])
        # drafts stay on device: the verify program consumes them directly
        return drafts, dlen
