"""Per-request adaptive draft length (ISSUE 5 tentpole, part 4).

Drafting is a bet: a verify block burns target-model compute on every
proposed position whether or not it lands. A request whose recent drafts
keep getting rejected (unpredictable continuation) should shrink its bet
toward 1; a request riding a predictable stretch (repetition, template,
copied span) should raise it back toward ``k_max``. The controller keeps
one acceptance-rate EMA per request — NOT per engine — because mixed
workloads routinely contain both regimes at once.

The draft width of the COMPILED verify program stays the static bucket
``k_max`` (one program, no recompiles as rates drift); adaptation only
changes how many of the k slots carry real proposals (``draft_len``),
which is a traced operand.
"""
from __future__ import annotations

from typing import Dict

__all__ = ["AdaptiveDraftController"]


class AdaptiveDraftController:
    def __init__(self, k_max: int, alpha: float = 0.4):
        self.k_max = max(1, int(k_max))
        self.alpha = float(alpha)
        self._ema: Dict[int, float] = {}  # rid -> acceptance-rate EMA

    def draft_len(self, req) -> int:
        """Drafts to propose for ``req`` this verify step."""
        remaining = req.max_new_tokens - len(req.tokens)
        if remaining <= 1:
            return 0  # the bonus token finishes the request; drafts waste
        # optimistic start (probe the full width), then track the EMA;
        # never below 1 — a zero-draft steady state could never observe
        # the acceptance recovering
        ema = self._ema.get(req.rid, 1.0)
        want = int(ema * self.k_max + 0.5)
        return max(1, min(self.k_max, want, remaining - 1))

    def update(self, req, proposed: int, accepted: int):
        if proposed <= 0:
            return
        rate = min(accepted, proposed) / proposed
        prev = self._ema.get(req.rid)
        self._ema[req.rid] = (rate if prev is None
                              else (1 - self.alpha) * prev
                              + self.alpha * rate)

    def rate(self, req) -> float:
        return self._ema.get(req.rid, 1.0)

    def forget(self, req):
        self._ema.pop(req.rid, None)
