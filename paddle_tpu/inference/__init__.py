"""Inference runtime (reference: paddle/fluid/inference/ —
api/analysis_predictor.cc, api/analysis_config.cc, paddle_inference_api.h;
Python surface paddle.inference.Config/create_predictor).

TPU-native (SURVEY.md A19): the reference loads a ProgramDesc, runs an IR
pass pipeline (fusion, TensorRT subgraph capture) and executes through
InterpreterCore. Here the saved artifact is already a compiled-friendly
StableHLO module (jit.save), XLA is the optimizer ("XLA replaces TRT"), and
the Predictor is a thin zero-copy runner with the reference's handle-based
API kept verbatim: get_input_names / get_input_handle / copy_from_cpu /
run / get_output_handle / copy_to_cpu.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor"]


class Config:
    """Reference: paddle.inference.Config. Accepts the jit.save prefix
    (``Config(prog_file, params_file)`` also accepted for signature parity —
    the prefix is derived from ``prog_file``)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".stablehlo.bin"):
            prog_file = prog_file[: -len(".stablehlo.bin")]
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._memory_pool_init_size_mb = 100
        self._device = "tpu"
        self._device_id = 0
        self._ir_optim = True

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self.__init__(prog_file, params_file)

    def model_dir(self):
        return self._prefix

    def prog_file(self):
        return self._prefix

    # compat no-ops (XLA owns these concerns)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device, self._device_id = "tpu", device_id

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self):
        pass

    def switch_ir_optim(self, x: bool = True):
        self._ir_optim = x

    def enable_tensorrt_engine(self, *a, **k):  # pragma: no cover
        raise NotImplementedError(
            "TensorRT is CUDA-only; XLA compiles the whole module on TPU "
            "(reference: inference/tensorrt/ — subsumed)"
        )


class Tensor:
    """Handle-based IO tensor (reference: paddle_infer.Tensor /
    ZeroCopyTensor)."""

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[jax.Array] = None

    def copy_from_cpu(self, arr):
        self._value = jnp.asarray(arr)

    def copy_to_cpu(self):
        if self._value is None:
            raise RuntimeError(f"output {self.name!r} not populated; run()?")
        return np.asarray(jax.device_get(self._value))

    def shape(self):
        return list(self._value.shape) if self._value is not None else None

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)


class Predictor:
    """Reference: analysis_predictor.cc AnalysisPredictor (Python:
    paddle_infer.Predictor). Wraps a loaded StableHLO artifact."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load

        if not config._prefix:
            raise ValueError("Config has no model path")
        self._translated = jit_load(config._prefix)
        n_in = len(self._translated._exported.in_avals) - len(
            jax.tree_util.tree_leaves(self._translated._state)
        )
        self._input_names = [f"x{i}" for i in range(max(n_in, 0))] or ["x0"]
        self._inputs: Dict[str, Tensor] = {
            n: Tensor(n) for n in self._input_names
        }
        self._outputs: List[Tensor] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def run(self, inputs: Optional[Sequence] = None):
        """Handle-based (reference style) or direct: ``run([np arrays]) ->
        [np arrays]``."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        args = []
        for n in self._input_names:
            h = self._inputs[n]
            if h._value is None:
                raise RuntimeError(f"input {n!r} not set")
            args.append(h._value)
        out = self._translated(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = []
        for i, o in enumerate(outs):
            t = Tensor(f"out{i}")
            t._value = o._data if hasattr(o, "_data") else jnp.asarray(o)
            self._outputs.append(t)
        if inputs is not None:
            return [t.copy_to_cpu() for t in self._outputs]
        return None

    def get_output_names(self) -> List[str]:
        return [t.name for t in self._outputs] or ["out0"]

    def get_output_handle(self, name: str) -> Tensor:
        for t in self._outputs:
            if t.name == name:
                return t
        raise KeyError(name)


def create_predictor(config: Config) -> Predictor:
    """Reference: paddle_infer.create_predictor."""
    return Predictor(config)
