"""Refcounted prefix cache for the paged serving engine (ISSUE 8 tentpole).

Production serving traffic is dominated by shared system prompts and
few-shot templates, so most prefill FLOPs recompute KV the pool already
holds. This module supplies the host-side index that turns a repeated
prefix into a page-table splice:

* **Chain hashing, page granularity.** A prefix is addressed block by
  block: block ``i``'s key is ``blake2b(parent_key || tokens_i)``, so a
  key commits to the ENTIRE token prefix up to and including its block —
  two prompts sharing only a suffix can never alias. Only FULL blocks
  (``page_size`` tokens) are cached; the partial tail page of a prompt is
  always recomputed (vLLM-style block hashing; SGLang's radix tree is the
  same reachability structure with keys instead of an explicit trie).
* **Hash-verify-on-hit.** Every entry stores its block's actual tokens and
  a lookup re-compares them, so even a blake2b collision (or a bug that
  mis-registered an entry) degrades to a cache miss, never to serving the
  wrong prefix.
* **Refcounts live with the OWNER.** The cache never owns pages: the
  engine's allocator keeps one refcount per physical page counting slot /
  pre-admission-row references, and the cache is an index over pages whose
  content is known. A page referenced only by the cache has refcount 0 —
  resident but idle — and is exactly what ``evict_lru`` reclaims under
  pool pressure. Pages with refcount > 0 are NEVER eviction candidates.
* **Leaf-first LRU eviction.** Evicting an interior block would strand its
  descendants (a lookup walks from the root, so an unreachable child can
  never be spliced again yet would pin its page); ``evict_lru`` therefore
  only considers entries with no cached children, oldest stamp first.
  Lookups re-stamp the whole matched chain, so ancestors are always at
  least as recent as their children and stale chains unwind tail-first.
* **Invalidate-on-doubt.** ``invalidate_page`` drops the entry backing a
  page AND every descendant (they are unreachable without the parent), so
  any corruption signal — the ``prefix-cache-corruption`` fault point, a
  failed integrity probe — costs future lookups a miss instead of wrong
  tokens. ``clear`` is the pool-reset flush (engine fault recovery must
  never serve pages whose backing buffers were rebuilt).
* **The byte-trust window (ISSUE 14).** The verify-on-hit token compare
  above proves the ENTRY is the right one — the host-side tokens stored
  at registration match the prompt being admitted. It proves nothing
  about the DEVICE BYTES the entry points at: between registration and a
  later splice the page may sit idle (refcount 0) for arbitrarily long,
  and a bit flipped in HBM during that window used to ride straight into
  the spliced table and decode as confidently wrong tokens. That window
  is now closed one layer up: the engine's ``IntegritySentinel``
  (``inference/integrity.py``) records a per-page checksum when a block
  registers and re-verifies it when the page is spliced
  (``Engine._splice_prefix``) or re-registered — a mismatch routes
  through this class's ``invalidate_page``, so the corruption degrades
  to a miss exactly like a hash collision does. This module stays
  device-blind on purpose; it only promises that every doubt signal has
  an invalidation path.

The class is pure host code (stdlib + numpy) and deliberately knows
nothing about jax, devices, or the engine: the engine (and the draft-LM
drafter, which runs the same machinery over its own pool) passes its
refcount array in where reclamation decisions need it.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PrefixCache"]


class _Entry:
    """One cached full block: a physical page plus the chain identity."""

    __slots__ = ("key", "page", "tokens", "parent", "children", "stamp")

    def __init__(self, key: bytes, page: int, tokens: np.ndarray,
                 parent: Optional[bytes], stamp: int):
        self.key = key
        self.page = int(page)
        self.tokens = tokens          # this block's page_size tokens
        self.parent = parent          # parent block's key (None at root)
        self.children: set = set()    # keys of cached child blocks
        self.stamp = stamp            # LRU clock at last touch


class PrefixCache:
    """Block-chain index from token prefixes to resident physical pages."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self._by_key: Dict[bytes, _Entry] = {}
        self._by_page: Dict[int, _Entry] = {}
        self._clock = 0
        # plain-int telemetry the owner mirrors into its metrics registry
        self.hits = 0        # lookups that matched >= 1 block
        self.misses = 0      # lookups that matched nothing
        self.evictions = 0   # pages reclaimed by evict_lru

    # ------------------------------------------------------------- keys
    def _chain(self, tokens: np.ndarray) -> List[Tuple[bytes, np.ndarray]]:
        """(key, block_tokens) for every FULL block of ``tokens``."""
        ps = self.page_size
        toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
        out = []
        parent = b""
        for i in range(toks.size // ps):
            block = toks[i * ps:(i + 1) * ps]
            key = hashlib.blake2b(parent + block.tobytes(),
                                  digest_size=16).digest()
            out.append((key, block))
            parent = key
        return out

    # ----------------------------------------------------------- lookup
    def lookup(self, tokens, touch: bool = True
               ) -> Tuple[List[int], int]:
        """Longest cached block-aligned prefix of ``tokens``. Returns
        ``(pages, matched_len)`` — ``matched_len`` is a multiple of
        ``page_size`` and ``pages`` the physical pages backing it, in
        block order. ``touch=False`` is a pure peek (capacity planning):
        no LRU re-stamp, no hit/miss accounting."""
        pages: List[int] = []
        matched = 0
        chain: List[_Entry] = []
        for key, block in self._chain(tokens):
            ent = self._by_key.get(key)
            if ent is None or not np.array_equal(ent.tokens, block):
                # missing, or a hash collision / stale entry caught by the
                # verify-on-hit token compare: stop at a miss
                break
            chain.append(ent)
            pages.append(ent.page)
            matched += self.page_size
        if touch:
            if chain:
                self._clock += 1
                for ent in chain:
                    ent.stamp = self._clock
                self.hits += 1
            else:
                self.misses += 1
        return pages, matched

    # --------------------------------------------------------- register
    def register(self, tokens, pages) -> int:
        """Publish the full blocks of ``tokens`` as backed by ``pages``
        (one physical page per block, block order). Existing entries win
        — a block already cached keeps its original page and the caller's
        page stays private (first-writer-wins dedup, so one content hash
        never maps to two pages). Returns the number of pages adopted."""
        adopted = 0
        self._clock += 1
        parent_ent: Optional[_Entry] = None
        for (key, block), page in zip(self._chain(tokens), pages):
            page = int(page)
            ent = self._by_key.get(key)
            if ent is not None:
                # verify-on-hit also guards registration: a colliding key
                # with different tokens must not chain through
                if not np.array_equal(ent.tokens, block):
                    break
                ent.stamp = self._clock
                parent_ent = ent
                continue
            if page <= 0 or page in self._by_page:
                # page 0 is the engine's trash page; a page can only back
                # one block's content
                break
            ent = _Entry(key, page, np.array(block, np.int32),
                         parent_ent.key if parent_ent is not None else None,
                         self._clock)
            self._by_key[key] = ent
            self._by_page[page] = ent
            if parent_ent is not None:
                parent_ent.children.add(key)
            parent_ent = ent
            adopted += 1
        return adopted

    # ---------------------------------------------------------- queries
    @property
    def n_pages(self) -> int:
        return len(self._by_page)

    def contains_page(self, page: int) -> bool:
        return int(page) in self._by_page

    def evictable_count(self, page_ref) -> int:
        """Upper bound on reclaimable pages: entries whose page has no
        live references. (An interior refcount-0 block above a pinned
        descendant is counted but not yet evictable — the shortfall
        surfaces as an allocation failure the caller already handles.)"""
        return sum(1 for p in self._by_page if not page_ref[p])

    # ---------------------------------------------------------- removal
    def _remove(self, ent: _Entry):
        del self._by_key[ent.key]
        self._by_page.pop(ent.page, None)
        if ent.parent is not None:
            parent = self._by_key.get(ent.parent)
            if parent is not None:
                parent.children.discard(ent.key)

    def evict_lru(self, page_ref) -> Optional[int]:
        """Reclaim ONE idle page: the oldest-stamped LEAF entry whose page
        has refcount 0. Returns the freed page id, or None when every
        cached page is either referenced or an interior block. Never
        touches a page any slot still references."""
        victim = None
        for ent in self._by_key.values():
            if ent.children or page_ref[ent.page]:
                continue
            if victim is None or ent.stamp < victim.stamp:
                victim = ent
        if victim is None:
            return None
        self._remove(victim)
        self.evictions += 1
        return victim.page

    def invalidate_page(self, page: int) -> List[int]:
        """Drop the entry backing ``page`` and every descendant block
        (unreachable without their parent). Returns the pages whose
        entries were dropped — the owner routes each by refcount (0 →
        free list, >0 → returns on release as usual)."""
        ent = self._by_page.get(int(page))
        if ent is None:
            return []
        stack, dropped = [ent], []
        while stack:
            e = stack.pop()
            stack.extend(self._by_key[k] for k in e.children
                         if k in self._by_key)
            self._remove(e)
            dropped.append(e.page)
        return dropped

    def clear(self) -> List[int]:
        """Flush everything (pool reset / fault recovery). Returns the
        previously cached pages."""
        pages = list(self._by_page)
        self._by_key.clear()
        self._by_page.clear()
        return pages
