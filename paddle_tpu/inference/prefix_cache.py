"""Refcounted prefix cache for the paged serving engine (ISSUE 8 tentpole).

Production serving traffic is dominated by shared system prompts and
few-shot templates, so most prefill FLOPs recompute KV the pool already
holds. This module supplies the host-side index that turns a repeated
prefix into a page-table splice:

* **Chain hashing, page granularity.** A prefix is addressed block by
  block: block ``i``'s key is ``blake2b(parent_key || tokens_i)``, so a
  key commits to the ENTIRE token prefix up to and including its block —
  two prompts sharing only a suffix can never alias. Only FULL blocks
  (``page_size`` tokens) are cached; the partial tail page of a prompt is
  always recomputed (vLLM-style block hashing; SGLang's radix tree is the
  same reachability structure with keys instead of an explicit trie).
* **Hash-verify-on-hit.** Every entry stores its block's actual tokens and
  a lookup re-compares them, so even a blake2b collision (or a bug that
  mis-registered an entry) degrades to a cache miss, never to serving the
  wrong prefix.
* **Refcounts live with the OWNER.** The cache never owns pages: the
  engine's allocator keeps one refcount per physical page counting slot /
  pre-admission-row references, and the cache is an index over pages whose
  content is known. A page referenced only by the cache has refcount 0 —
  resident but idle — and is exactly what ``evict_lru`` reclaims under
  pool pressure. Pages with refcount > 0 are NEVER eviction candidates.
* **Leaf-first LRU eviction.** Evicting an interior block would strand its
  descendants (a lookup walks from the root, so an unreachable child can
  never be spliced again yet would pin its page); ``evict_lru`` therefore
  only considers entries with no cached children, oldest stamp first.
  Lookups re-stamp the whole matched chain, so ancestors are always at
  least as recent as their children and stale chains unwind tail-first.
* **Invalidate-on-doubt.** ``invalidate_page`` drops the entry backing a
  page AND every descendant (they are unreachable without the parent), so
  any corruption signal — the ``prefix-cache-corruption`` fault point, a
  failed integrity probe — costs future lookups a miss instead of wrong
  tokens. ``clear`` is the pool-reset flush (engine fault recovery must
  never serve pages whose backing buffers were rebuilt).
* **Tiered entries (ISSUE 15).** With the host-DRAM spill tier armed,
  eviction becomes DEMOTION: the victim entry stays in the index but its
  ``tier`` leaves ``"hbm"`` (``"spilling"`` while the background copy is
  in flight, ``"host"`` once the bytes land in the host slab,
  ``"promoting"`` while a copy back is in flight) and its device page is
  surrendered for reuse. ``lookup`` splices only the HBM-resident chain
  prefix — a demoted block is a MISS for this admission (the request
  rides partial-prefill for the suffix) but ``tiers=True`` additionally
  returns the matched demoted entries so the owner can promote them for
  the next one. LRU stamps span the tiers (one clock), demotion picks
  HBM victims whose children are already off-HBM (the index keeps every
  entry reachable, so demotion — unlike removal — can never strand a
  descendant), and host-capacity eviction drops oldest leaf-first
  exactly like the old device-tier eviction did. This class still knows
  nothing about devices or bytes: tier strings and host slots are
  opaque bookkeeping the owner (``kv_tier.HostTier``) drives, and the
  ``owner_release`` callback tells that owner when an entry leaves the
  index (or re-binds to a device page) so host slots can be reclaimed.
* **The byte-trust window (ISSUE 14).** The verify-on-hit token compare
  above proves the ENTRY is the right one — the host-side tokens stored
  at registration match the prompt being admitted. It proves nothing
  about the DEVICE BYTES the entry points at: between registration and a
  later splice the page may sit idle (refcount 0) for arbitrarily long,
  and a bit flipped in HBM during that window used to ride straight into
  the spliced table and decode as confidently wrong tokens. That window
  is now closed one layer up: the engine's ``IntegritySentinel``
  (``inference/integrity.py``) records a per-page checksum when a block
  registers and re-verifies it when the page is spliced
  (``Engine._splice_prefix``) or re-registered — a mismatch routes
  through this class's ``invalidate_page``, so the corruption degrades
  to a miss exactly like a hash collision does. This module stays
  device-blind on purpose; it only promises that every doubt signal has
  an invalidation path.

The class is pure host code (stdlib + numpy) and deliberately knows
nothing about jax, devices, or the engine: the engine (and the draft-LM
drafter, which runs the same machinery over its own pool) passes its
refcount array in where reclamation decisions need it.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PrefixCache", "chain_keys"]


def chain_keys(tokens, page_size: int) -> List[bytes]:
    """The chain-hash keys for every FULL block of ``tokens``.

    Block ``i``'s key is ``blake2b(parent_key || tokens_i)``, so a key
    commits to the entire prefix through its block. This is the SAME
    derivation ``PrefixCache._chain`` uses — it is public so the cluster
    router (``serving/cluster.py``) can score a prompt against the
    chain digests replicas report in their readiness payload without
    holding a cache instance: matching hex keys means matching token
    prefixes, replica-independently."""
    ps = int(page_size)
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
    out: List[bytes] = []
    parent = b""
    for i in range(toks.size // ps):
        block = toks[i * ps:(i + 1) * ps]
        key = hashlib.blake2b(parent + block.tobytes(),
                              digest_size=16).digest()
        out.append(key)
        parent = key
    return out


class _Entry:
    """One cached full block: a physical page plus the chain identity."""

    __slots__ = ("key", "page", "tokens", "parent", "children", "stamp",
                 "tier", "hslot", "job")

    def __init__(self, key: bytes, page: int, tokens: np.ndarray,
                 parent: Optional[bytes], stamp: int):
        self.key = key
        self.page = int(page)
        self.tokens = tokens          # this block's page_size tokens
        self.parent = parent          # parent block's key (None at root)
        self.children: set = set()    # keys of cached child blocks
        self.stamp = stamp            # LRU clock at last touch
        # host-DRAM tier state (ISSUE 15): "hbm" entries back a live
        # device page; demotion walks hbm -> spilling -> host and
        # promotion host -> promoting -> hbm. hslot is the host-slab
        # row while host-resident; job is an owner-issued token so a
        # stale async completion (the entry moved on) is discarded.
        self.tier: str = "hbm"
        self.hslot: Optional[int] = None
        self.job: int = 0


class PrefixCache:
    """Block-chain index from token prefixes to resident physical pages."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self._by_key: Dict[bytes, _Entry] = {}
        self._by_page: Dict[int, _Entry] = {}
        self._clock = 0
        # plain-int telemetry the owner mirrors into its metrics registry
        self.hits = 0        # lookups that matched >= 1 block
        self.misses = 0      # lookups that matched nothing
        self.evictions = 0   # pages reclaimed by evict_lru
        # host-tier owner hook (ISSUE 15): called with the entry whenever
        # its host-side residency ends without the owner's own promote
        # path doing it — removal from the index, or a re-bind back to a
        # device page. The owner reclaims the host slot and invalidates
        # any in-flight async job. None when no tier is armed.
        self.owner_release = None

    # ------------------------------------------------------------- keys
    def _chain(self, tokens: np.ndarray) -> List[Tuple[bytes, np.ndarray]]:
        """(key, block_tokens) for every FULL block of ``tokens``."""
        ps = self.page_size
        toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
        keys = chain_keys(toks, ps)
        return [(key, toks[i * ps:(i + 1) * ps])
                for i, key in enumerate(keys)]

    # ----------------------------------------------------------- lookup
    def lookup(self, tokens, touch: bool = True, tiers: bool = False):
        """Longest cached HBM-RESIDENT block-aligned prefix of
        ``tokens``. Returns ``(pages, matched_len)`` — ``matched_len``
        is a multiple of ``page_size`` and ``pages`` the physical pages
        backing it, in block order. ``touch=False`` is a pure peek
        (capacity planning): no LRU re-stamp, no hit/miss accounting.

        With a host tier armed a chain can continue past the HBM prefix
        through demoted entries; those are a miss for THIS splice (their
        device bytes are gone — the request recomputes the suffix via
        partial prefill) but ``tiers=True`` returns them as a third
        element ``(pages, matched_len, demoted)`` so the owner can
        request an async promote-back — the hash-chain hit on a demoted
        page the tier turns into a future splice. Touching re-stamps
        the demoted continuation too: content a request just asked for
        is the warmest kind, whichever tier holds it."""
        pages: List[int] = []
        matched = 0
        chain: List[_Entry] = []
        demoted: List[_Entry] = []
        for key, block in self._chain(tokens):
            ent = self._by_key.get(key)
            if ent is None or not np.array_equal(ent.tokens, block):
                # missing, or a hash collision / stale entry caught by the
                # verify-on-hit token compare: stop at a miss
                break
            if demoted or ent.tier != "hbm":
                # past the first non-HBM block nothing splices (the
                # chain must be contiguous from the root); keep walking
                # only to find what the tier should promote
                demoted.append(ent)
                continue
            chain.append(ent)
            pages.append(ent.page)
            matched += self.page_size
        if touch:
            if chain or demoted:
                self._clock += 1
                for ent in chain:
                    ent.stamp = self._clock
                for ent in demoted:
                    ent.stamp = self._clock
            # a splice-able HBM prefix is a hit; a purely demoted match
            # is THIS admission's miss (it recomputes), however warm the
            # host tier is — the tier's own hit counter tells that story
            if chain:
                self.hits += 1
            else:
                self.misses += 1
        if tiers:
            return pages, matched, demoted
        return pages, matched

    # --------------------------------------------------------- register
    def register(self, tokens, pages) -> int:
        """Publish the full blocks of ``tokens`` as backed by ``pages``
        (one physical page per block, block order). Existing entries win
        — a block already cached keeps its original page and the caller's
        page stays private (first-writer-wins dedup, so one content hash
        never maps to two pages). Returns the number of pages adopted."""
        adopted = 0
        self._clock += 1
        parent_ent: Optional[_Entry] = None
        for (key, block), page in zip(self._chain(tokens), pages):
            page = int(page)
            ent = self._by_key.get(key)
            if ent is not None:
                # verify-on-hit also guards registration: a colliding key
                # with different tokens must not chain through
                if not np.array_equal(ent.tokens, block):
                    break
                if ent.tier != "hbm" and page > 0 \
                        and page not in self._by_page:
                    # recompute-as-promote (ISSUE 15): the block's bytes
                    # were just recomputed onto ``page`` because the
                    # demoted copy couldn't splice — re-binding the
                    # entry to the fresh device page IS the promotion,
                    # minus the copy. The owner_release hook reclaims
                    # the host slot and orphans any in-flight job.
                    self._rebind(ent, page)
                ent.stamp = self._clock
                parent_ent = ent
                continue
            if page <= 0 or page in self._by_page:
                # page 0 is the engine's trash page; a page can only back
                # one block's content
                break
            ent = _Entry(key, page, np.array(block, np.int32),
                         parent_ent.key if parent_ent is not None else None,
                         self._clock)
            self._by_key[key] = ent
            self._by_page[page] = ent
            if parent_ent is not None:
                parent_ent.children.add(key)
            parent_ent = ent
            adopted += 1
        return adopted

    # ---------------------------------------------------------- queries
    @property
    def n_pages(self) -> int:
        return len(self._by_page)

    def contains_page(self, page: int) -> bool:
        return int(page) in self._by_page

    def evictable_count(self, page_ref) -> int:
        """Upper bound on reclaimable pages: entries whose page has no
        live references. (An interior refcount-0 block above a pinned
        descendant is counted but not yet evictable — the shortfall
        surfaces as an allocation failure the caller already handles.)"""
        return sum(1 for p in self._by_page if not page_ref[p])

    # ---------------------------------------------------------- removal
    def _remove(self, ent: _Entry):
        del self._by_key[ent.key]
        self._by_page.pop(ent.page, None)
        # any async tier job for this entry is now stale, and its host
        # slot (if any) must return to the owner's free list
        ent.job += 1
        if self.owner_release is not None:
            self.owner_release(ent)
        if ent.parent is not None:
            parent = self._by_key.get(ent.parent)
            if parent is not None:
                parent.children.discard(ent.key)

    def _lru_victim(self, page_ref) -> Optional[_Entry]:
        """The reclamation victim shared by eviction and demotion: the
        oldest-stamped HBM entry whose page has refcount 0 and whose
        cached children (if any) are all off-HBM already — with no tier
        that degenerates to the classic leaf-first rule, and with one
        it lets a whole chain drain to the host tail-first without ever
        stranding a still-spliceable descendant."""
        victim = None
        for ent in self._by_key.values():
            if ent.tier != "hbm" or page_ref[ent.page]:
                continue
            if any(self._by_key[k].tier == "hbm" for k in ent.children
                   if k in self._by_key):
                continue
            if victim is None or ent.stamp < victim.stamp:
                victim = ent
        return victim

    def evict_lru(self, page_ref) -> Optional[int]:
        """Reclaim ONE idle page: the oldest-stamped LEAF entry whose page
        has refcount 0. Returns the freed page id, or None when every
        cached page is either referenced or an interior block. Never
        touches a page any slot still references."""
        victim = self._lru_victim(page_ref)
        if victim is None:
            return None
        self._remove(victim)
        self.evictions += 1
        return victim.page

    # ------------------------------------------------- tier transitions
    def take_for_demotion(self, page_ref):
        """Demotion twin of :meth:`evict_lru` (ISSUE 15): pick the same
        LRU victim, surrender its device page to the caller, but KEEP
        the entry — ``tier="spilling"`` until the background copy lands
        in the host slab. Returns ``(page, entry)`` or ``None``. The
        device-tier eviction counter still ticks: from the paged pool's
        point of view the page was reclaimed either way."""
        victim = self._lru_victim(page_ref)
        if victim is None:
            return None
        page = victim.page
        del self._by_page[page]
        victim.page = 0
        victim.tier = "spilling"
        victim.job += 1
        self.evictions += 1
        return page, victim

    def promote(self, ent: _Entry, page: int) -> bool:
        """Re-bind a host-resident entry to a freshly promoted device
        page (the owner verified + copied the bytes). False when the
        entry has meanwhile left the index or the page is already
        mapped — the owner rolls its copy back."""
        if self._by_key.get(ent.key) is not ent \
                or int(page) in self._by_page:
            return False
        ent.tier = "hbm"
        ent.hslot = None
        ent.job += 1
        ent.page = int(page)
        self._by_page[ent.page] = ent
        # freshly promoted = freshly wanted: re-stamp so the page is not
        # the very next demotion victim (its old stamp predates the
        # demotion that parked it)
        self._clock += 1
        ent.stamp = self._clock
        return True

    def _rebind(self, ent: _Entry, page: int):
        """Recompute-as-promote: re-bind a demoted entry to a device
        page that just had its exact content recomputed (register's
        existing-entry path). Ends the entry's host residency — the
        owner_release hook reclaims the slot and stales the job."""
        ent.job += 1
        if self.owner_release is not None:
            self.owner_release(ent)
        ent.tier = "hbm"
        ent.hslot = None
        ent.page = int(page)
        self._by_page[ent.page] = ent

    def evict_host_lru(self) -> Optional[_Entry]:
        """Reclaim ONE host slab slot: drop the oldest host-resident
        entry with NO cached children in any tier (dropping an interior
        block would strand descendants the index can still reach).
        Returns the removed entry (its slot comes back through
        owner_release) or None."""
        victim = None
        for ent in self._by_key.values():
            if ent.tier != "host" or ent.children:
                continue
            if victim is None or ent.stamp < victim.stamp:
                victim = ent
        if victim is None:
            return None
        self._remove(victim)
        return victim

    def invalidate_entry(self, ent: _Entry) -> List[int]:
        """Invalidate-on-doubt for an entry that has no device page to
        key on (a demoted block whose promotion failed its checksum):
        same descendants-too walk as :meth:`invalidate_page`."""
        if self._by_key.get(ent.key) is not ent:
            return []
        return self._invalidate_from(ent)

    def invalidate_page(self, page: int) -> List[int]:
        """Drop the entry backing ``page`` and every descendant block
        (unreachable without their parent). Returns the pages whose
        entries were dropped — the owner routes each by refcount (0 →
        free list, >0 → returns on release as usual)."""
        ent = self._by_page.get(int(page))
        if ent is None:
            return []
        return self._invalidate_from(ent)

    def _invalidate_from(self, ent: _Entry) -> List[int]:
        stack, dropped = [ent], []
        while stack:
            e = stack.pop()
            stack.extend(self._by_key[k] for k in e.children
                         if k in self._by_key)
            self._remove(e)
            if e.page:
                dropped.append(e.page)
        return dropped

    def clear(self) -> List[int]:
        """Flush everything (pool reset / fault recovery). Returns the
        previously cached DEVICE pages (demoted entries have none; their
        host slots return through owner_release)."""
        pages = list(self._by_page)
        if self.owner_release is not None:
            for ent in self._by_key.values():
                ent.job += 1
                self.owner_release(ent)
        self._by_key.clear()
        self._by_page.clear()
        return pages
