"""Host-DRAM spill tier under the paged KV pool (ISSUE 15 tentpole).

At millions-of-users scale the prefix cache (ISSUE 8) is HBM-bound:
refcount-0 cached pages are evicted leaf-first exactly when the working
set outgrows the paged pool, throwing away the reuse that makes caching
pay. Mooncake-style KV tiering and vLLM's paged swapping show the fix —
host DRAM is ~100x HBM for KV purposes, and one PCIe/ICI page copy is
far cheaper than recomputing the page's prefill FLOPs — so eviction
becomes DEMOTION and a later hash-chain hit becomes PROMOTION:

* **Demote (device→host, async).** When the allocator reclaims an idle
  cached page, the engine thread dispatches a tiny jitted gather of that
  page's bytes out of every layer's K/V (and scale) buffer into fresh
  arrays (``ModelRunner.capture_pages`` — an async dispatch, never a
  sync) and hands the handles to the background spill worker. The
  worker — the ONLY place in the serving stack allowed to block on a
  device→host page transfer (tpulint TPL1101 enforces this) — fetches
  the bytes, records a blake2b digest over them, and writes them into
  its host slab row. The physical page was surrendered to the new owner
  the moment the gather was dispatched, so demotion never delays an
  allocation; the prefix-cache entry rides ``spilling → host``.
* **Promote (host→device, async, checksum-verified).** A lookup that
  matches into demoted blocks cannot splice them (their device bytes
  are gone) — the request rides partial prefill for that suffix, a
  MISS, never a stall — but it queues a promote: the worker re-reads
  the slab row, re-hashes it against the digest recorded at demotion
  (a bit flipped while the page sat in host DRAM — the
  ``kv-spill-corrupt`` fault point — fails here and costs an
  invalidate + recompute, never a token), and posts the verified
  payload. The engine thread then allocates a device page and restores
  the bytes with one batched ``_copy_pages``-style donated dispatch
  (``ModelRunner.restore_pages``), re-binds the entry to it, and — when
  the integrity sentinel is armed — re-adopts the page's device-side
  checksum so the ISSUE 14 splice-time probe keeps guarding promoted
  pages exactly like never-demoted ones.
* **Recompute-as-promote.** If a request recomputes a demoted block
  before its promotion lands (the common first-touch race), harvest-
  time registration re-binds the entry to the freshly computed page
  and the in-flight promotion is discarded by its job token — both
  paths converge on identical bytes, so streams are bit-identical
  tier-on vs tier-off by construction.

All prefix-cache and allocator state stays engine-thread-only: the
worker communicates exclusively through the job queue (in) and the
completion deque (out, drained by the engine thread at step / admission
boundaries). The host slab is worker-owned; a slab row is written only
by the spill job that was assigned it and read only by promote jobs,
and jobs are FIFO, so no row is ever touched by two jobs concurrently.

Lifecycle: ``reset()`` (pool reset after an engine-scoped fault) drops
the WHOLE tier — host copies describe trust established before the
fault, and the recompute policy makes them free to re-earn — and
``stop()`` (frontend drain/shutdown, replica quarantine/restart) ends
the worker thread so a restarted replica never inherits a stale spill
pipeline.
"""
from __future__ import annotations

import hashlib
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

__all__ = ["HostTier", "bench_kv_tier", "capture_handoff_spill"]

# capture/restore waves use one fixed index width (mirrors
# HostTier.COPY_WIDTH): a per-wave width would mint a fresh XLA program
# per distinct size
_HANDOFF_COPY_WIDTH = 32


def capture_handoff_spill(engine, tokens) -> Optional[dict]:
    """Capture the prompt's cached KV pages into a host-side handoff
    slab (ISSUE 20): the cross-replica twin of the demotion capture
    above. Engine thread; BLOCKS on the device→host fetch — the name
    carries the ``spill`` hint because this is a deliberate second
    blocking-copy site (tpulint TPL1101), invoked only on the cluster's
    dedicated handoff thread via ``ServingFrontend.call``, never from
    the scheduling loop.

    Returns the wire payload — per-page buffer rows in ``pages_flat``
    order plus a per-page blake2b digest (chain-contiguous from the
    root, so the importer can truncate at the first mismatch) and the
    integrity sentinel's device-side sums — or ``None`` when nothing is
    cached for the prompt (the caller falls back to recompute). Only
    the HBM-resident chain prefix ships: host-tier tails would need a
    promote round trip that costs more than the recompute they save."""
    import jax
    import jax.numpy as jnp

    coord = getattr(engine, "_cache", None)
    pc = getattr(engine, "_pcache", None)
    if coord is None or pc is None:
        return None
    pages, matched = pc.lookup(tokens, touch=False)
    if not pages:
        return None
    ps = int(pc.page_size)
    ig = getattr(engine, "_integrity", None)
    w = _HANDOFF_COPY_WIDTH
    rows_per_page: List[List[np.ndarray]] = []
    for off in range(0, len(pages), w):
        chunk = pages[off:off + w]
        idx = np.zeros((w,), np.int32)
        idx[:len(chunk)] = chunk
        handles = engine.runner.capture_pages(coord.pages_flat(),
                                              jnp.asarray(idx))
        arrays = [np.asarray(jax.device_get(h)) for h in handles]
        for j in range(len(chunk)):
            rows_per_page.append([np.array(a[j]) for a in arrays])
    digests, nbytes = [], 0
    for rows in rows_per_page:
        d = hashlib.blake2b(digest_size=16)
        for a in rows:
            d.update(a.tobytes())
            nbytes += a.nbytes
        digests.append(d.hexdigest())
    dev_sums = [None if ig is None else ig.sum_of_page(p) for p in pages]
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
    return {
        "tokens": [int(t) for t in toks[:matched]],
        "page_size": ps,
        "digests": digests,
        "pages": rows_per_page,
        "dev_sums": dev_sums,
        "nbytes": int(nbytes),
    }


def _pow2ceil(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class HostTier:
    """Background host-DRAM spill tier; see module docstring. Owned by
    the :class:`~paddle_tpu.inference.cache_coord.CacheCoordinator`;
    every public method except the worker loop runs on the engine
    thread."""

    def __init__(self, coord, host_pages: int):
        self.coord = coord
        self.engine = coord.engine
        self.host_pages = int(host_pages)
        self._free_hslots: List[int] = list(range(self.host_pages - 1,
                                                  -1, -1))
        self._digest: Dict[int, bytes] = {}    # hslot -> blake2b digest
        self._dev_sum: Dict[int, float] = {}   # hslot -> sentinel sum
        self._gen = 0                          # bumped by reset()
        self._slabs: Optional[List[np.ndarray]] = None  # worker-owned
        self._q: "queue.Queue" = queue.Queue()
        self._done: deque = deque()            # worker -> engine thread
        self._done_evt = threading.Event()     # set on every completion
        self._pending: List = []               # demotions awaiting capture
        self._stopped = False
        # plain-int telemetry (mirrored into the metrics registry by the
        # record sites below; kept here so tests/benches can read the
        # tier's story without a scrape)
        self.demotions = 0   # pages spilled device -> host
        self.promotions = 0  # pages restored host -> device
        self.hits = 0        # lookups that reached host-tier content
        self.drops = 0       # demoted blocks lost (capacity/corruption)
        pc = coord.pcache
        pc.owner_release = self.release_entry
        self._worker = threading.Thread(
            target=self._worker_loop, name="paddle-kv-spill", daemon=True)
        self._worker.start()

    # ------------------------------------------------------------ metrics
    @property
    def _m(self):
        # getattr: the coordinator (and its construction-time reset)
        # builds before the engine's metrics bundle exists
        return getattr(self.engine, "_m", None)

    def _update_occupancy(self):
        m = self._m
        if m is not None:
            m.kv_tier_pages.labels(tier="host").set(
                self.host_pages - len(self._free_hslots))
            m.kv_tier_pages.labels(tier="hbm").set(
                self.coord.pcache.n_pages)

    # ----------------------------------------------------- engine thread
    def demote(self, page: int, ent) -> None:
        """Queue ``ent``'s spill: its bytes are still resident in device
        page ``page``, which the allocator is handing to a new owner.
        Nothing is dispatched here — demotions accumulate and ONE
        batched capture gather goes out in :meth:`flush_captures`,
        which every dispatch path triggers through
        ``CacheCoordinator.pages_flat()`` BEFORE any program could
        overwrite the page (the ``_flush_cow`` idiom). When the host
        tier itself is full and nothing in it is droppable, the block
        is dropped outright (counted; exactly what the un-tiered cache
        did on every eviction)."""
        hslot = self._alloc_hslot()
        if hslot is None:
            self.drops += 1
            if self._m is not None:
                self._m.kv_drops.inc()
            # no host room: the demotion degenerates to the classic
            # eviction — remove the entry (and any stranded descendants)
            self._drop_entry(ent)
            return
        dev_sum = None
        ig = getattr(self.engine, "_integrity", None)
        if ig is not None:
            # the sentinel's device-side checksum travels with the bytes
            # so a verified promotion can re-adopt it (ISSUE 14 probes
            # keep covering the page after its round trip); read NOW —
            # the allocator forgets it the moment the page re-homes
            dev_sum = ig.sum_of_page(page)
        ent.hslot = hslot
        self.demotions += 1
        if self._m is not None:
            self._m.kv_demotions.inc()
        self._pending.append((int(page), ent, ent.job, hslot, dev_sum))
        self._update_occupancy()

    # capture/restore dispatches use ONE fixed index width (padded with
    # page 0, the trash page; longer waves chunk): a per-wave pow2 width
    # would mint a fresh XLA program per distinct size, and on the
    # single-core smoke host every such compile is tens of ms landing
    # straight in the serving path (memory: one cold compile ≈ 1 s in
    # p99). Two programs total — one gather, one scatter — forever.
    COPY_WIDTH = 32

    def flush_captures(self, pages_list) -> None:
        """Dispatch batched page-gathers for every queued demotion
        (engine thread; ``pages_list`` is the coordinator's CURRENT
        buffer list, passed raw to avoid recursing through
        ``pages_flat``). Async: the worker gets device handles, the
        engine thread never blocks."""
        if not self._pending:
            return
        import jax.numpy as jnp

        batch, self._pending = self._pending, []
        w = self.COPY_WIDTH
        for off in range(0, len(batch), w):
            chunk = batch[off:off + w]
            idx = np.zeros((w,), np.int32)
            idx[:len(chunk)] = [p for p, *_ in chunk]
            handles = self.engine.runner.capture_pages(pages_list,
                                                       jnp.asarray(idx))
            self._q.put(("spill", self._gen,
                         [(ent, token, hslot, dev_sum)
                          for _, ent, token, hslot, dev_sum in chunk],
                         handles))

    def request_promote(self, entries) -> None:
        """Queue async promote-backs for host-resident entries a lookup
        just matched (the hash-chain hit on demoted pages). Entries
        mid-spill or already promoting are left alone — their in-flight
        job is the promotion. Never blocks; the requesting admission
        rides partial prefill either way."""
        queued = False
        for ent in entries:
            if ent.tier != "host" or ent.hslot is None:
                continue
            ent.tier = "promoting"
            self._q.put(("promote", self._gen, ent, ent.job, ent.hslot,
                         self._digest.get(ent.hslot),
                         self._dev_sum.get(ent.hslot),
                         time.perf_counter()))
            queued = True
        if queued:
            # one hit per lookup that actually started promotions (a
            # re-touch of an already-promoting chain is the same hit)
            self.hits += 1
            if self._m is not None:
                self._m.kv_tier_hits.inc()

    # a splice may briefly wait for an in-flight promotion: the wait is
    # bounded WELL below the prefill recompute it avoids (one host
    # memcpy + hash vs re-running the model over the whole block), so
    # it is a scheduling micro-pause, not a stall — and a promote that
    # overruns it (the slow-host-copy fault point, a genuinely slow
    # host) degrades this admission to a partial-prefill miss
    PROMOTE_WAIT_S = 0.02

    def await_promotions(self, entries, budget_s: Optional[float] = None
                         ) -> None:
        """Bounded drain-wait for in-flight promotions of ``entries``
        (engine thread). Returns as soon as none are ``promoting`` or
        the budget lapses — NEVER unbounded: a slow promote leaves the
        entries in flight and the caller recomputes them as a miss."""
        budget = self.PROMOTE_WAIT_S if budget_s is None else budget_s
        deadline = time.monotonic() + budget
        while any(e.tier == "promoting" for e in entries):
            left = deadline - time.monotonic()
            if left <= 0:
                return
            self._done_evt.wait(left)
            self._done_evt.clear()
            self.drain()

    def drain(self) -> None:
        """Apply worker completions (engine thread, step/admission
        boundaries): land finished spills as ``host`` entries, splice
        verified promotions back into the device pool — all of a
        drain's promotions through ONE batched restore dispatch — and
        contain checksum failures as invalidate + recompute-as-miss."""
        pc = self.coord.pcache

        def current(ent, token):
            return ent.job == token and pc._by_key.get(ent.key) is ent

        promotes = []
        while True:
            try:
                msg = self._done.popleft()
            except IndexError:
                break
            kind, gen = msg[0], msg[1]
            if gen != self._gen:
                continue  # predates a reset; owner_release cleaned up
            if kind == "spill":
                for ent, token, hslot, digest, dev_sum in msg[2]:
                    if not current(ent, token):
                        continue  # moved on (e.g. recompute re-bind)
                    ent.tier = "host"
                    self._digest[hslot] = digest
                    if dev_sum is not None:
                        self._dev_sum[hslot] = dev_sum
            elif kind == "promote":
                _, _, ent, token, hslot, payload, dev_sum, dt = msg
                if current(ent, token):
                    promotes.append((ent, hslot, payload, dev_sum, dt))
            else:  # "promote-bad" / "fault": doubt the block
                ent, token = msg[2], msg[3]
                if current(ent, token):
                    self._contain_bad(ent)
        if promotes:
            self._land_promotions(promotes)
        self._update_occupancy()

    def _land_promotions(self, promotes) -> None:
        """Splice a drain's verified promotions back into the pool with
        one batched ``_copy_pages``-style donated dispatch."""
        pc = self.coord.pcache
        landed = []
        for ent, hslot, payload, dev_sum, dt in promotes:
            page = self.coord.alloc_page()
            if page is None:
                # pool genuinely full even after demotion pressure: stay
                # host-resident, a future lookup re-requests
                ent.tier = "host"
                continue
            landed.append((ent, int(page), hslot, payload, dev_sum, dt))
        if not landed:
            return
        import jax.numpy as jnp

        w = self.COPY_WIDTH
        for off in range(0, len(landed), w):
            chunk = landed[off:off + w]
            m = len(chunk)
            idx = np.zeros((w,), np.int32)
            idx[:m] = [page for _, page, *_ in chunk]
            stacked = [
                np.stack([lan[3][i] for lan in chunk]
                         + [np.zeros_like(chunk[0][3][i])] * (w - m))
                for i in range(len(chunk[0][3]))
            ]
            # pages_flat() flushes queued captures first, so a page the
            # alloc above just demoted is read BEFORE this restore
            # writes its new bytes (jax orders dispatches by data
            # dependency); pad rows re-write the trash page
            self.coord.set_pages(self.engine.runner.restore_pages(
                self.coord.pages_flat(), jnp.asarray(idx), stacked))
        ig = getattr(self.engine, "_integrity", None)
        for ent, page, hslot, _payload, dev_sum, dt in landed:
            # the entry owns the page from here (idle cached: ref 0)
            self.coord.page_ref[page] = 0
            self._free_hslot(hslot)
            ent.hslot = None
            if not pc.promote(ent, page):
                # raced out of the index between the token check and
                # now (not reachable today — single-threaded — but a
                # freed page must never leak)
                self.coord.free_pages.append(page)
                continue
            if ig is not None and dev_sum is not None:
                ig.adopt_page_sum(page, dev_sum)
            self.promotions += 1
            if self._m is not None:
                self._m.kv_promotions.inc()
                self._m.kv_promote_seconds.observe(dt)

    def _contain_bad(self, ent):
        """A promotion failed its checksum (or the worker faulted on the
        job): invalidate-on-doubt — the entry and every descendant drop,
        future lookups recompute-as-miss, and the failure is counted on
        the integrity surface. Never a wrong token: the corrupt bytes
        were never spliced."""
        self.drops += 1
        if self._m is not None:
            self._m.kv_drops.inc()
        self._drop_entry(ent)

    def _drop_entry(self, ent):
        """Remove ``ent`` + descendants from the index, routing freed
        device pages (a descendant may still be HBM-resident) exactly
        like every other invalidation path."""
        eng = self.engine
        ig = getattr(eng, "_integrity", None)
        for p in self.coord.pcache.invalidate_entry(ent):
            if ig is not None:
                ig.forget_page(p)
            if int(self.coord.page_ref[p]) == 0:
                self.coord.free_pages.append(p)

    # hooks -----------------------------------------------------------
    def release_entry(self, ent) -> None:
        """``PrefixCache.owner_release``: the entry left the index or
        re-bound to a device page — reclaim its host slot (in-flight
        jobs die by token; FIFO job order makes a stale slab write
        harmless to any later reassignment of the row)."""
        if ent.hslot is not None:
            self._free_hslot(ent.hslot)
            ent.hslot = None
            self._update_occupancy()

    def _alloc_hslot(self) -> Optional[int]:
        if self._free_hslots:
            return self._free_hslots.pop()
        victim = self.coord.pcache.evict_host_lru()
        if victim is not None:
            # _remove fired release_entry, so the free list has a slot
            self.drops += 1
            if self._m is not None:
                self._m.kv_drops.inc()
        return self._free_hslots.pop() if self._free_hslots else None

    def _free_hslot(self, hslot: int):
        self._digest.pop(hslot, None)
        self._dev_sum.pop(hslot, None)
        self._free_hslots.append(hslot)

    # lifecycle -------------------------------------------------------
    def reset(self):
        """Pool reset (engine fault recovery): drop the whole tier. The
        host copies were captured from a pool that just died mid-fault;
        the recompute policy makes them free to re-earn, and never
        serving spill state that predates a fault is the same trust
        posture the device cache takes (``PrefixCache.clear``)."""
        self._gen += 1
        self._free_hslots = list(range(self.host_pages - 1, -1, -1))
        self._digest.clear()
        self._dev_sum.clear()
        self._done.clear()
        self._pending = []  # un-captured demotions die with the pool
        self._update_occupancy()

    def stop(self, timeout: float = 5.0):
        """End the worker thread (frontend drain/shutdown, replica
        quarantine/restart). Idempotent; pending jobs are abandoned —
        the tier is bookkeeping over recomputable bytes, so there is
        nothing to flush."""
        if self._stopped:
            return
        self._stopped = True
        self._gen += 1
        self._q.put(None)
        self._worker.join(timeout=timeout)

    # ----------------------------------------------------- worker thread
    def _worker_loop(self):
        """The spill worker: the one blocking device→host copy site in
        the serving stack, deliberately off the engine thread so a slow
        host copy (the ``slow-host-copy`` fault point) degrades hits to
        misses instead of stalling scheduling."""
        while True:
            job = self._q.get()
            if job is None:
                return
            fi = self.engine._fi
            if fi is not None and fi.fire("slow-host-copy"):
                time.sleep(fi.param("slow-host-copy", "delay_ms", 25.0)
                           / 1e3)
            try:
                self._worker_job(job)
            except Exception:  # noqa: BLE001 - worker isolation: a
                # failed copy must doubt the block, never kill the tier
                self._post_fault(job)
            self._done_evt.set()

    def _post_fault(self, job):
        """Route a worker-side failure into containment: the completion
        drives :meth:`_contain_bad` on the engine thread (invalidate +
        recompute-as-miss + drop accounting) — a faulted copy doubts
        the block, it never silently parks it. A spill job carries a
        WAVE of (ent, token, ...) items where a promote job carries one
        entry inline — post one fault per entry, or the drain's
        ``ent.job == token`` check would choke on the raw item list
        (found by the ISSUE 19 ``_done``-drain audit: the old
        single-message form was promote-shaped only)."""
        if job[0] == "spill":
            for ent, token, _hslot, _dev_sum in job[2]:
                self._done.append(("fault", job[1], ent, token))
        else:
            self._done.append(("fault", job[1], job[2], job[3]))

    def _worker_job(self, job):
        import jax

        fi = self.engine._fi
        if fi is not None and fi.fire("racey-worker-write"):
            # deliberate ownership violation (ISSUE 19 satellite): poke
            # an engine-owned counter from the worker, bypassing the
            # job-queue/completion-deque channel. setattr keeps the
            # write invisible to the static tpurace pass (reflection is
            # a documented blind spot) — proving the RUNTIME guard
            # covers what the linter cannot: with ownership_guard()
            # armed this raises OwnershipError, the worker isolation
            # above routes it through _post_fault, and the engine drain
            # contains the job as a counted drop (chaos-asserted).
            # Guard off: value-identical no-op.
            setattr(self, "demotions", self.demotions + 0)
        kind = job[0]
        if kind == "spill":
            _, gen, items, handles = job
            # one blocking fetch for the whole demotion wave: each
            # handle is [m_pad, page_size, lanes] for one K/V/scale
            # buffer (device_get assembles the global logical pages —
            # at tp>1 the lanes arrive shard-assembled)
            arrays = [np.asarray(jax.device_get(h)) for h in handles]
            if self._slabs is None:
                self._slabs = [
                    np.zeros((self.host_pages,) + a.shape[1:], a.dtype)
                    for a in arrays]
            done = []
            for j, (ent, token, hslot, dev_sum) in enumerate(items):
                digest = hashlib.blake2b(digest_size=16)
                for slab, a in zip(self._slabs, arrays):
                    slab[hslot] = a[j]
                    digest.update(a[j].tobytes())
                done.append((ent, token, hslot, digest.digest(),
                             dev_sum))
            # the engine thread stores the digests/dev_sums at drain so
            # a stale completion can't poison a reassigned row
            self._done.append(("spill", gen, done))
        else:  # promote
            _, gen, ent, token, hslot, want, dev_sum, t0 = job
            fi = self.engine._fi
            if fi is not None and fi.fire("kv-spill-corrupt"):
                # SILENT host-DRAM damage (ISSUE 15 satellite): flip one
                # seed-chosen byte of the host-resident page — nothing
                # signals doubt, only the digest below stands between
                # this flip and a wrong token
                row = self._slabs[0][hslot]
                view = row.view(np.uint8).reshape(-1)
                view[fi.draw("kv-spill-corrupt", view.size)] ^= 0xFF
            payload = [np.array(s[hslot]) for s in self._slabs]
            digest = hashlib.blake2b(digest_size=16)
            for a in payload:
                digest.update(a.tobytes())
            ok = want is not None and digest.digest() == want
            from .integrity import count_integrity_check

            count_integrity_check("kv_tier", ok)
            if ok:
                self._done.append(
                    ("promote", gen, ent, token, hslot, payload, dev_sum,
                     time.perf_counter() - t0))
            else:
                self._done.append(("promote-bad", gen, ent, token))


# --------------------------------------------------------------- benchmark
def bench_kv_tier(cfg, on_tpu: bool):
    """bench.py ``bench_kv_tier`` block (ISSUE 15 satellite): a
    templated-overlap workload whose CACHED working set is ~10x the
    paged pool — the regime where the un-tiered prefix cache collapses
    (every template is reclaimed before its next visit) and the host
    tier keeps paying. Round-robin template visits with distinct tails,
    closed-loop (submit + step), so promote prefetch overlaps queue
    wait exactly as in serving.

    The model is sized so a template's prefill is genuinely expensive
    relative to a page copy (hidden 384: the compute a hit skips grows
    ~quadratically with width, the bytes the tier moves only linearly —
    at toy widths the single-core host spends as long hashing/copying
    as it would recomputing and the comparison measures nothing).

    Gates (CPU smoke green; the host is single-core, so the throughput
    comparison is an interleaved-rep ratio of medians floored at the
    50 ms jitter floor — no absolute-latency gates):

    * sustained prefix hit-rate >= 0.8 tier-on where tier-off stays
      < 0.2 — the headline: reuse survives a working set the HBM pool
      cannot hold;
    * effective prefill throughput (prompt tokens ingested/s over the
      measured passes) tier-on >= tier-off (ratio >= 1.0): splices +
      page copies must beat recompute even on a host where the copy,
      the hash, and the compute all share one core;
    * > 0 promotions and 0 drops (every round trip verified clean)."""
    from ..models.gpt import GPTConfig, GPTForCausalLM
    from .engine import Engine

    del cfg  # the block sizes its own config (CPU smoke parity)
    import jax.numpy as jnp

    from .. import seed as _seed

    _seed(0)
    mcfg = GPTConfig(hidden_size=384, num_layers=2, num_heads=4,
                     max_position=256, vocab_size=512)
    model = GPTForCausalLM(mcfg)
    model.eval()

    ps, slots, num_pages = 16, 2, 24
    n_templates, template_len, tail_len, budget = 21, 144, 16, 2
    host_pages = 512
    rng = np.random.default_rng(7)
    templates = [rng.integers(0, 512, (template_len,))
                 for _ in range(n_templates)]
    work_pages = n_templates * (template_len // ps)
    ws_ratio = work_pages / (num_pages - 1)

    def make(hp):
        return Engine(model, max_slots=slots, num_pages=num_pages,
                      page_size=ps, chunk_size=4, dtype=jnp.float32,
                      prefix_cache=True, kv_host_pages=hp)

    seed = [0]

    def round_once(eng):
        reqs = []
        for t in range(n_templates):
            seed[0] += 1
            r = np.random.default_rng(10_000 + seed[0])
            prompt = np.concatenate(
                [templates[t], r.integers(0, 512, (tail_len,))])
            reqs.append(eng.add_request(prompt, budget))
            eng.step()
            eng.step()
        eng.run()
        return sum(int(q.prompt.size) for q in reqs)

    engines = {"on": make(host_pages), "off": make(0)}
    for eng in engines.values():
        round_once(eng)  # warmup: compiles + first cache fill
    marks = {k: (e._pcache.hits, e._pcache.misses)
             for k, e in engines.items()}
    reps, times, ptoks = 3, {"on": [], "off": []}, {"on": 0, "off": 0}
    for _ in range(reps):
        for key, eng in engines.items():
            t0 = time.perf_counter()
            ptoks[key] += round_once(eng)
            times[key].append(time.perf_counter() - t0)

    floor_s = 0.020 if on_tpu else 0.050
    med = {k: max(float(np.median(v)), floor_s)
           for k, v in times.items()}
    thr = {k: ptoks[k] / (med[k] * reps) for k in engines}
    ratio = thr["on"] / thr["off"] if thr["off"] else 0.0
    rates = {}
    for key, eng in engines.items():
        h0, m0 = marks[key]
        pc = eng._pcache
        dh, dm = pc.hits - h0, pc.misses - m0
        rates[key] = dh / max(1, dh + dm)
    tier = engines["on"].kv_tier
    ok = (rates["on"] >= 0.8 and rates["off"] < 0.2 and ratio >= 1.0
          and tier.promotions > 0 and tier.drops == 0)
    if not ok:
        print(f"WARNING: bench_kv_tier gate failed: hit_rate_on="
              f"{rates['on']:.3f} (>=0.8), hit_rate_off="
              f"{rates['off']:.3f} (<0.2), throughput_ratio="
              f"{ratio:.3f} (>=1.0), promotions={tier.promotions} "
              f"(>0), drops={tier.drops} (==0)")
    out = {
        "kv_tier_working_set_x_pool": round(ws_ratio, 2),
        "kv_tier_hit_rate_on": round(rates["on"], 3),
        "kv_tier_hit_rate_off": round(rates["off"], 3),
        "kv_tier_prefill_ratio": round(ratio, 3),
        "kv_tier_prefill_tokens_per_sec": round(thr["on"], 1),
        "kv_tier_prefill_tokens_per_sec_off": round(thr["off"], 1),
        "kv_tier_demotions": int(tier.demotions),
        "kv_tier_promotions": int(tier.promotions),
        "kv_tier_drops": int(tier.drops),
        "kv_tier_jitter_floor_ms": 1e3 * floor_s,
        "kv_tier_ok": bool(ok),
    }
    engines["on"]._cache.shutdown_tier()
    return out
