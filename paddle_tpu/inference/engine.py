"""Continuous-batching serving engine over the paged KV cache.

Reference capability: the serving loop behind
``paddle/fluid/inference/api/analysis_predictor.cc`` driving
``fused_multi_transformer_op.cu`` decode passes (SURVEY A19 + A3.x) —
request admission, KV cache management, decode scheduling, streaming
output. TPU-first design instead of a C++ executor loop:

* **Slots + pages.** ``max_slots`` sequence slots share one page pool per
  layer (vLLM-style block tables). A finished request's pages recycle
  immediately; physical page 0 is reserved as the trash page idle slots
  write into, so the compiled step needs no active-slot branching.
* **Compiled chunks, host scheduling.** Decode runs ``chunk_size`` steps
  per dispatch as ONE jitted ``lax.scan`` over functional
  ``PagedCacheState`` pytrees (block tables and lengths are traced
  operands — no recompile as requests come and go). The host only runs
  between chunks: harvest tokens, finish/free, admit, top up page
  allocations.
* **Chunk chaining (VERDICT r3 #1).** On a tunneled TPU a dispatch costs
  ~50–100 ms against ~20 ms of chunk compute, so fetching after every
  chunk is dispatch-latency-bound. ``step`` therefore dispatches up to
  ``max_chain`` chunks back-to-back on device arrays (each chunk's
  carry feeds the next without a host round trip) and fetches ALL their
  tokens in one ``device_get``. The chain depth maximizes USEFUL tokens
  per unit time (see ``_chain_depth``): stragglers may overshoot their
  budget mid-chain — overshoot tokens are harvested away, their writes
  land in trash/recycled pages, and the cache-write path caps lengths
  at the table capacity so overshoot can never run the attention kernel
  out of bounds. Pages are pre-allocated for the whole chain (capped at
  each request's own budget).
* **Batched admission, fused into the step (VERDICT r3 #1, r4 #2).**
  ALL admissible queued requests prefill in ONE bucketed dispatch: rows
  pad to the fixed max_slots bucket, prompts to a shared pow2 length
  bucket (capped at ``max_position`` so position ids never index past
  the embedding table), padding rows write to the trash page. The
  prefill dispatches back-to-back with the decode chain — the chain's
  inputs splice the prefill's device outputs — and ONE blocking fetch
  harvests both, so a scheduling step costs a single host round trip.
* **Pre-admission (VERDICT r4 #2).** When completions are predictable
  (no eos: budgets are host-known), the queue heads that will take over
  this chain's completing slots prefill DURING the chain, into freshly
  allocated pages; at harvest they activate into the freed slots with
  warm caches. Slot turnover then needs no extra round trip, and the
  straggler chain-depth clamp is only needed when an eos makes
  completions unpredictable. Measured: the whole mixed bench workload
  serves in 2 scheduling steps at ~81% of steady-state decode
  throughput (r4: 29%; full-process bench.py run recorded 7.7k steady / 6.0k serve = 78%).
* **Measured chain-boundary cost (VERDICT r4 #2).** Chain depth
  maximizes useful tokens per unit time against a MEASURED
  dispatch+fetch cost (EMA-fitted from warm pure-decode step timings,
  with a strictly bounded neighboring-depth probe when the workload is
  single-depth); ``DISPATCH_COST_CHUNKS_PRIOR`` seeds the estimate only
  until data arrives, so the same code picks sane depths on a tunneled
  chip (~8 chunks/boundary) and a direct-attached one (~0).
* **Active-slot buckets (VERDICT r3 #1).** The compiled decode chunk is
  sized to the pow2 bucket of the ACTIVE slot count, not ``max_slots``:
  the host compacts active slots' tables/lengths/last-token rows,
  decodes the compact batch, and scatters results back. At low
  occupancy per-token cost tracks load, not capacity.
* **Sampling (VERDICT r3 #9).** Per-request ``temperature`` (0 = greedy
  argmax — bit-identical to the contiguous path) with optional engine-
  level ``top_k``; per-slot PRNG keys thread through the compiled scan,
  and the key state survives preemption, so a preempted sampled request
  resumes with exactly the tokens it would have produced uninterrupted.
* **No head-of-line blocking.** Admission fills any free slot while other
  slots keep decoding; short requests drain and recycle their pages while
  long ones continue.
* **Speculative decoding (ISSUE 5).** ``Engine(..., spec="ngram"|"draft",
  spec_k=k)`` swaps the chained decode for drafter→verify scheduling:
  a pluggable drafter (model-free prompt lookup, or a small draft LM
  over its own paged pool) proposes up to k tokens, ONE verify forward
  through the same paged path scores all k+1 positions, and acceptance
  (token-exact for greedy — output identical to vanilla decode;
  distribution-preserving rejection sampling for temp>0) lands 1..k+1
  tokens per step. Rejected rows roll back through ``_trim_pages``;
  per-request draft depth adapts to an acceptance-rate EMA. See
  ``paddle_tpu/inference/spec/`` and README "Speculative decoding".
* **Fault tolerance (ISSUE 6).** ``step()`` never raises. Request-scoped
  faults — validation, page-pool exhaustion, non-finite logits (an
  in-program isfinite guard rides every compiled program), drafter
  faults, deadline/TTL expiry, cancellation, streaming-callback errors —
  move ONE request to the terminal ``FAILED`` state with a taxonomy
  reason (``paddle_tpu/inference/errors.py``) while co-batched requests
  keep decoding bit-identically to a fault-free run. Engine-scoped
  faults (a compiled dispatch dies) trigger requeue-all recompute
  recovery (prefixes re-prefill, PRNG keys travel — the preemption
  machinery reused wholesale) and feed the watchdog
  (``paddle_tpu/inference/watchdog.py``), which degrades spec→vanilla
  and halves the admission cap rather than dying, probing back up when
  healthy. Admission is bounded (``max_queue`` backpressure, per-request
  ``deadline_s``/``cancel()``, ``max_retries`` recompute bound with
  front-of-queue aging). Every failure path is drivable deterministically
  through the named fault-injection points
  (``paddle_tpu/testing/faultinject.py``, ``FLAGS_fault_inject``) and
  proven by ``tests/test_fault_tolerance.py`` (``make chaos``).
* **Chunked prefill (ISSUE 9).** ``Engine(..., prefill_chunk=N)`` stops
  long prompts from stalling the decode batch: instead of one bucketed
  prefill dispatch sized to the longest prompt, prompts stream into the
  cache N tokens at a time through a FIXED-SHAPE mixed step — one
  compiled program (the fused verify/suffix slab attention path,
  ``paged_multi_query_attention``) advances EVERY active slot each
  dispatch: decoding slots by one token (a width-1 slab row), prefilling
  slots by one chunk. One program shape per sampling flag, so a cold
  server compiles (or cache-loads) a couple of programs instead of a
  prefill bucket per prompt-length pow2 — first-wave throughput
  approaches steady state — and decode tokens keep landing every step
  while a 32k-token prompt trickles in (the Sarathi/vLLM chunked-prefill
  schedule). The final chunk's logits produce the request's first token
  exactly where classic prefill would, sampled key burns are gated to
  token-emitting rows, and the prefix cache splices/registers precisely
  as in the unchunked path — output streams are identical chunked on or
  off (``tests/test_chunked_prefill.py``, ``make chaos``).
* **Tensor-parallel serving (ISSUE 11).** The engine is split into
  engine-core (THIS module: the host scheduler — admission, harvest,
  retries, watchdog; device-count-agnostic), model-runner
  (``inference/runner.py``: the compiled programs and, with
  ``Engine(tp=N)``, the TP mesh they trace under — weights column/
  row-sharded via ``shard_map``, the paged pool sharded by KV head,
  host operands replicated) and cache-coordinator
  (``inference/cache_coord.py``: pool + refcount allocator + prefix
  cache; page tables host-global, device buffers per-shard). On top,
  ``Engine(disaggregate=True)`` separates prefill/decode ROLES within
  a scheduling step: mid-prompt slots stream chunks through the mixed
  program while decoding slots ride deep chains, one harvest fence,
  pages handed over through the shared pool. Token streams are
  bit-identical to the single-chip engine in every mode
  (``tests/test_tp_serving.py``); the sharded programs are statically
  gated by tpushard (``make analyze --mesh 1 --mesh 4 --mesh 8``).
* **Multi-step scheduling (ISSUE 12).** ``Engine(multi_step=N)`` (or an
  explicit ``step(n=N)``) amortizes the host round trip over N decode
  iterations: in pure-decode phases (queue empty, spec off, no prompt
  mid-stream) the scheduler dispatches N chained-decode programs
  BACK-TO-BACK — each chain's device outputs (pages, lengths, PRNG
  keys, last token) feed the next with no host fetch between — and
  harvests all N with ONE blocking ``device_get``. The Orca
  iteration-level-scheduling move: host work (numpy packing, harvest,
  metrics, the step spine) is paid once per N iterations instead of
  per iteration. Token streams are BIT-IDENTICAL to ``multi_step=1``
  in every mode (greedy, sampled, spec, chunked, disaggregated, TP —
  ``tests/test_multi_step.py``, ``make chaos``): per-row computation is
  unchanged, chains compose exactly as sequential steps would, and the
  harvest walks the chains in order with the same per-request isolation
  — early-exiting the moment the active set drains (eos/budget/fault),
  so later chains' rows for finished requests are discarded exactly
  like chain overshoot. Steps that must consult the host every
  iteration (admission waves, mixed chunk scheduling, spec drafting)
  keep classic stepping; ``paddle_tpu_engine_steps_per_roundtrip``
  records how many iterations each round trip actually batched.
* **Data integrity (ISSUE 14).** ``Engine(integrity="audit"|"strict")``
  arms the :class:`~paddle_tpu.inference.integrity.IntegritySentinel`
  against SILENT data corruption — the failure class where nothing
  raises and the engine streams confidently wrong tokens: load-time
  per-tensor weight digests re-checked by a periodic idle-step shard
  audit (mismatch → sticky watchdog QUARANTINE: the engine fail-stops,
  ``/readyz`` drops, the router migrates streams and supervised-
  restarts with verified weights); per-page KV checksums recorded at
  prefix-cache registration and re-verified before every splice
  commits (mismatch → invalidate-on-doubt + preempt active referents —
  corruption costs a miss or an exact-resume recompute, never a
  token); and, in strict mode, an every-N-steps shadow recompute of
  one greedy row through the contiguous twin (divergence → that
  request fails typed). Drive it with the ``bit-flip-weight`` /
  ``bit-flip-kv`` fault points; ``make chaos-integrity`` asserts no
  injected flip ever reaches a delivered token. See README "Data
  integrity".
* **Continuous telemetry (ISSUE 3).** Every scheduling step records the
  vLLM/Orca-style operational surface into the process-global metrics
  registry (``paddle_tpu.observability``): TTFT/TPOT/queue-wait
  histograms, batch-occupancy and chain-depth distributions, preemption
  and page-eviction counters, page-pool gauges. All recording is host
  code between dispatches (never traced — tpulint TPL601), costs ~4 µs
  per step (<1% of decode throughput, ``tools/mb_metrics.py``), and is
  disabled wholesale by ``Engine(..., metrics=False)``. Scrape it via
  ``observability.start_metrics_server`` (see
  ``examples/serve_llama_paged.py --metrics-port``).

The engine is model-agnostic: anything with the causal-LM cache contract
(``forward(ids, caches=..., time_step=None)`` handling ``PagedCacheState``,
plus ``config`` with num_layers / num_kv_heads / head_dim) serves — GPT and
LLaMA both qualify.
"""
from __future__ import annotations

import contextlib
import functools
import hashlib
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, pause_tape
from ..observability.tracing import TRACER as _TRACER
from ..observability.tracing import flight_record as _flight_record
from ..ops.pallas.paged_attention import PagedCacheState
from ..testing.faultinject import FaultPlan, InjectedFault, plan_from_flags
from .errors import (
    AdmissionRejected,
    CallbackError,
    CancelledError,
    DeadlineExceeded,
    NumericsError,
    PoolExhausted,
    QueueFull,
    RequestError,
    RetriesExhausted,
    StepFault,
    ValidationError,
    failure_reason,
)
from .watchdog import Watchdog


@jax.jit
def _advance_sample_key(key, burns):
    """Replay ``burns`` sampling-key splits host-free (one fori_loop
    dispatch, ``burns`` a traced scalar so every count shares one
    compiled program). The vanilla decode/prefill paths burn EXACTLY one
    ``jax.random.split`` per DELIVERED token for a temp>0 request (see
    ``_select_token``: ``new_keys = splits[:, 0]``; chunked prefill is
    emit-gated the same way), so a stream migrated to another replica
    with only (prompt, emitted tokens, seed) in hand can reconstruct its
    live key state as ``split^t(seed_key)[0]`` — the resume-from-emitted
    admission path (ISSUE 13). Spec decode burns a fixed k+2 keys per
    VERIFY STEP instead (step count is not recoverable from the token
    count), which is why ``add_request`` rejects sampled resumes on a
    spec-enabled engine."""
    return jax.lax.fori_loop(
        0, burns, lambda _, k: jax.random.split(k, 2)[0], key)


@jax.jit
def _patch_rows(last_c, keys_c, rows, toks, keys):
    """Splice a prefill wave's first tokens and PRNG keys into the decode
    chain's compacted inputs ON DEVICE — the glue that lets freshly
    admitted requests join the same step's chain without the host ever
    fetching the prefill results separately. Pad rows carry an
    out-of-bounds index and drop. (jit caches per shape by itself.)"""
    return (last_c.at[rows].set(toks, mode="drop"),
            keys_c.at[rows].set(keys, mode="drop"))


@jax.jit
def _last_col(toks):
    """Final token column of a chain's [nb, steps] output block — the
    next chain's last-token input in a multi-step round trip (ISSUE 12).
    Jitted: the eager dynamic-slice dispatch costs ~10x a cached jit
    call on the hot path (measured ~46% of the multi-step loop)."""
    return toks[:, -1]


@functools.partial(jax.jit, donate_argnums=(0,))
def _copy_pages(pages_flat, src, dst):
    """Copy-on-write page duplication ON DEVICE: physical pages ``src``
    copied to ``dst`` across every layer's k/v (and scale) buffers in one
    dispatch — the whole admission wave's COW set at once. Donated so the
    pool updates in place."""
    return [p.at[dst].set(p[src]) for p in pages_flat]


def _pow2ceil(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@contextlib.contextmanager
def _moe_tap(n: int):
    """Arm the MoE router-stats tap around ONE ``model.forward`` when
    the engine serves an MoE config (``n`` = stats width,
    ``moe_stats_size(cfg)``; 0 = dense engine, no-op). Yields the
    per-layer stats list the MoE layers append to (traced arrays — the
    raw program sums them into its trailing stats output)."""
    # tpulint: disable=TPL301 -- n is a static Python int (the config's
    # stats width, fixed at program-build time), never a tracer; the
    # branch selects program STRUCTURE (dense vs MoE), not a data path
    if not n:
        yield None
        return
    from ..models.llama import moe_stats_tap

    with moe_stats_tap() as tap:
        yield tap


def make_mixed_step_fn(engine, sampling):
    """Build the raw mixed chunk+decode step (ISSUE 9 tentpole b) — the
    fixed-shape program ``Engine(prefill_chunk=)`` dispatches every
    scheduling step. ``ids [nb, chunk]`` carries, per row, EITHER the
    next chunk of a streaming prompt (width w ≤ chunk) OR a decoding
    slot's last token (width 1); ``paged_state_verify`` (verify=True +
    per-row ``prefill_valid`` widths) writes each row's w tokens at
    [len, len+w) and scores every position over cache + causal prefix
    through ``paged_multi_query_attention`` — the fused slab kernel on
    TPU, its jnp twin elsewhere. The token at position w-1 is the row's
    next token: meaningful for decode rows and for a prompt's FINAL
    chunk (the first generated token, taken exactly where classic
    prefill takes it); mid-prompt rows discard it. ``emit`` gates the
    sampled-key burn to token-emitting rows, so a sampled stream burns
    exactly one draw per delivered token — the invariant that makes
    chunked-on output bit-identical to chunked-off.

    Returns the UNJITTED python function (the engine wraps it with
    ``jax.jit(donate_argnums=(1,))``); the tpucheck registry traces the
    same raw function (``tools/analyze_tpu.py`` entry
    ``chunked_prefill_step``)."""
    model = engine.model
    moe_n = getattr(engine, "_moe_stats_n", 0)

    def mixed_chunk_step(params, pages_flat, ids, widths, emit, tables,
                         lengths, temps, keys):
        from ..jit import swapped_tensors

        with swapped_tensors(engine._swap, params), pause_tape():
            states = engine._states_from(pages_flat, tables, lengths,
                                         prefill_valid=widths,
                                         verify=True)
            with _moe_tap(moe_n) as tap:
                logits, new_states = model.forward(Tensor._wrap(ids),
                                                   caches=states)
            lg = logits._data if isinstance(logits, Tensor) else logits
            last = jnp.take_along_axis(
                lg, (widths - 1)[:, None, None], axis=1)[:, 0]
            last = last.astype(jnp.float32)
            # NaN/inf logit guard (ISSUE 6): the host fails THAT request
            bad = ~jnp.all(jnp.isfinite(last), axis=-1)
            greedy = jnp.argmax(last, axis=-1).astype(jnp.int32)
            if sampling:
                tok, burned = engine._select_token(last, greedy, temps,
                                                   keys)
                new_keys = jnp.where((emit > 0)[:, None], burned, keys)
            else:
                tok, new_keys = greedy, keys
            out = tok, new_keys, bad, engine._pages_of(new_states)
            if moe_n:
                out += (jnp.sum(jnp.stack(tap), axis=0),)
            return out

    return mixed_chunk_step


@dataclass
class Request:
    rid: int
    prompt: np.ndarray  # [S] int32
    max_new_tokens: int
    on_token: Optional[Callable] = None  # streaming callback(list[int])
    temperature: float = 0.0  # 0 → greedy argmax
    seed: Optional[int] = None  # sampling seed (None → rid)
    # multi-tenant serving (ISSUE 12): the admission-control/fairness
    # identity; labels the TTFT/queue-wait/failure metrics (bounded
    # cardinality — see _EngineMetrics._tenant_label)
    tenant: str = "default"
    tokens: List[int] = field(default_factory=list)  # generated tokens
    done: bool = False
    slot: Optional[int] = None
    # lifecycle hardening (ISSUE 6):
    deadline: Optional[float] = None   # absolute perf_counter deadline
    retries: int = 0                   # recompute re-queues so far
    failure: Optional[BaseException] = None  # taxonomy error on FAILED
    failure_reason: Optional[str] = None     # its stable reason slug
    _key: Optional[np.ndarray] = None  # live PRNG key (survives preemption)
    # request tracing (ISSUE 18): parent SpanContext wire string the
    # engine's spans/instants nest under; None when tracing is off or
    # the caller didn't propagate one
    trace: Optional[str] = None
    # telemetry timestamps (host wall clock, perf_counter units):
    _t_arrival: float = 0.0          # add_request time (TTFT base)
    _t_submit: Optional[float] = None  # upstream submit time (placement)
    _t_admit: Optional[float] = None   # slot admission (prefill base)
    _t_promote_wait: float = 0.0       # KV-tier promote wait inside admit
    _t_first: Optional[float] = None   # first generated-token harvest
    _t_last: Optional[float] = None    # latest harvest (TPOT base)
    _admitted: bool = False            # queue-wait recorded once

    @property
    def failed(self) -> bool:
        return self.failure_reason is not None

    @property
    def state(self) -> str:
        """Lifecycle state: QUEUED → ACTIVE → FINISHED | FAILED.
        FAILED is terminal and carries ``failure_reason`` (the taxonomy
        slug) + ``failure`` (the exception)."""
        if self.failed:
            return "FAILED"
        if self.done:
            return "FINISHED"
        if self.slot is not None:
            return "ACTIVE"
        return "QUEUED"


class _EngineMetrics:
    """The engine's serving telemetry bundle (ISSUE 3 tentpole). Every
    record site lives in the scheduler's HOST code — between dispatches,
    never inside traced functions (tpulint TPL601). Metrics are process-
    global (the registry get-or-creates by name), so several engines in
    one process aggregate into one scrape — the Prometheus convention."""

    def __init__(self):
        from ..observability import SIZE_BUCKETS, counter, gauge, histogram

        # TTFT/queue-wait/failures carry a ``tenant`` label (ISSUE 12
        # satellite) so per-tenant SLOs are scrape-visible; engine-direct
        # traffic lands on the "default" tenant. Cardinality is bounded:
        # past _TENANT_CAP distinct tenants, new ones share "other".
        self.ttft = histogram(
            "paddle_serving_ttft_seconds",
            "request arrival to first generated token, by tenant",
            labelnames=("tenant",))
        self.tpot = histogram(
            "paddle_serving_tpot_seconds",
            "mean inter-token latency per harvest (time-per-output-token)")
        self.queue_wait = histogram(
            "paddle_serving_queue_wait_seconds",
            "request arrival to slot admission, by tenant",
            labelnames=("tenant",))
        # TTFT latency attribution (ISSUE 18): the components partition
        # [submit, first-token] exactly on one perf_counter clock —
        # placement (upstream submit → engine arrival) + queue_wait
        # (arrival → admission, minus promote) + promote_wait (KV-tier
        # promotions awaited during admission splice) + prefill
        # (admission → first harvest) sum to the observed TTFT.
        self.ttft_component = histogram(
            "paddle_serving_ttft_component_seconds",
            "TTFT decomposition: placement|queue_wait|promote_wait|"
            "prefill component of arrival-to-first-token",
            labelnames=("component",))
        self.step_seconds = histogram(
            "paddle_serving_step_seconds",
            "wall time of one scheduling step (dispatch+harvest fence)")
        self.prefill_batch = histogram(
            "paddle_serving_prefill_batch_size",
            "requests per bucketed prefill wave", buckets=SIZE_BUCKETS)
        self.decode_batch = histogram(
            "paddle_serving_decode_batch_size",
            "active slots per decode chain dispatch", buckets=SIZE_BUCKETS)
        self.chain_depth = counter(
            "paddle_serving_chain_depth_total",
            "decode chains dispatched, by chosen chunk depth",
            labelnames=("depth",))
        self.preemptions = counter(
            "paddle_serving_preemptions_total",
            "requests evicted under page-pool pressure (recompute policy)")
        self.page_evictions = counter(
            "paddle_serving_page_evictions_total",
            "KV pages recycled by preemption")
        self.requests = counter(
            "paddle_serving_requests_total", "requests accepted")
        self.completed = counter(
            "paddle_serving_requests_completed_total", "requests finished")
        self.tokens = counter(
            "paddle_serving_tokens_total", "generated tokens delivered")
        self.compiled = counter(
            "paddle_serving_compiled_programs_total",
            "engine programs compiled, by kind", labelnames=("kind",))
        self.pages_in_use = gauge(
            "paddle_serving_pages_in_use", "KV pages currently allocated")
        self.pages_total = gauge(
            "paddle_serving_pages_total", "allocatable KV pages in the pool")
        self.active_slots = gauge(
            "paddle_serving_active_slots", "slots currently decoding")
        self.queue_depth = gauge(
            "paddle_serving_queue_depth", "requests waiting for a slot")
        # fault-tolerance surface (ISSUE 6): the reason label mirrors the
        # error-taxonomy slugs in inference/errors.py one-to-one
        self.failures = counter(
            "paddle_tpu_request_failures_total",
            "requests moved to terminal FAILED, by taxonomy reason and "
            "tenant", labelnames=("reason", "tenant"))
        self.admission_rejected = counter(
            "paddle_tpu_admission_rejected_total",
            "requests rejected at add_request (validation, capacity, "
            "queue backpressure)")
        self.retries = counter(
            "paddle_tpu_request_retries_total",
            "recompute re-queues (preemption or step-fault recovery)")
        self.recoveries = counter(
            "paddle_tpu_engine_recoveries_total",
            "whole-step fault recoveries (requeue-all + page-pool reset)")
        self.degraded = gauge(
            "paddle_tpu_engine_degraded",
            "degraded-mode level: 0 healthy, 1 spec decode disabled, "
            "2 admission cap halved on top")
        # readiness export (ISSUE 13): the /readyz surface and the
        # router's health gate read this — 1 while the watchdog judges
        # the engine fit for NEW traffic (level < SMALL_BATCH)
        self.ready = gauge(
            "paddle_tpu_engine_ready",
            "watchdog readiness: 1 = accepting new traffic, 0 = "
            "degraded past the readiness threshold (in-flight work "
            "still completes)")
        # prefix-cache surface (ISSUE 8): admission hit/miss, the cached-
        # vs-computed prefill-token split, pressure evictions, and the
        # pool share the cache currently holds
        self.pc_hits = counter(
            "paddle_tpu_prefix_cache_hits_total",
            "admissions that spliced a cached block-aligned prefix")
        self.pc_misses = counter(
            "paddle_tpu_prefix_cache_misses_total",
            "admissions that found no cached prefix")
        self.pc_evictions = counter(
            "paddle_tpu_prefix_cache_evictions_total",
            "idle cached pages reclaimed under pool pressure (LRU)")
        self.pc_cached_tokens = counter(
            "paddle_tpu_prefix_cached_prefill_tokens_total",
            "prefill tokens served from cached pages (compute skipped)")
        self.pc_computed_tokens = counter(
            "paddle_tpu_prefix_computed_prefill_tokens_total",
            "prefill tokens actually computed by a prefill wave")
        self.pc_pages = gauge(
            "paddle_tpu_prefix_cache_pages",
            "physical pages currently mapped by the prefix cache "
            "(pool share = this / paddle_serving_pages_total)")
        # decode hot-path kernel surface (ISSUE 9): how many prompt
        # chunks streamed through the mixed step, and which paths
        # dispatched the fused verify/suffix slab program (the label
        # mirrors the three consumers: spec verify, prefix-cache suffix
        # prefill, chunked prefill)
        # expert-parallel MoE serving surface (ISSUE 17): capacity-drop
        # pressure, per-expert routing load (bounded labels), and the
        # router's distribution entropy (collapse detector: uniform
        # routing sits at ln(num_experts), a collapsed router near 0)
        self.moe_dropped = counter(
            "paddle_tpu_moe_tokens_dropped_total",
            "(token, expert-choice) pairs dropped by the capacity "
            "factor; combine weights renormalize over the survivors")
        self.moe_expert_tokens = counter(
            "paddle_tpu_moe_expert_tokens_total",
            "routed (token, choice) pairs kept per expert (bounded "
            "cardinality: experts past the cap share 'other')",
            labelnames=("expert",))
        self.moe_router_entropy = gauge(
            "paddle_tpu_moe_router_entropy_nats",
            "mean router-distribution entropy of the most recently "
            "drained MoE dispatches")
        self._moe_expert_children: Dict[int, object] = {}
        self.prefill_chunks = counter(
            "paddle_tpu_prefill_chunks_total",
            "prompt chunks admitted into the mixed chunk+decode step")
        self.slab_dispatch = counter(
            "paddle_tpu_slab_verify_dispatch_total",
            "multi-query slab-attention programs dispatched, by path "
            "(the fused Pallas kernel on TPU, its jnp twin on CPU)",
            labelnames=("path",))
        # KV host-tier surface (ISSUE 15): the demote/promote ladder
        # under the prefix cache — spills to host DRAM, checksum-
        # verified restores, lookups that reached host-resident content,
        # blocks lost to host-capacity pressure or a failed promote
        # digest, per-tier page occupancy, and how long a promotion
        # spent between the hit that requested it and the verified
        # payload landing back on device
        self.kv_demotions = counter(
            "paddle_tpu_kv_tier_demotions_total",
            "idle cached KV pages spilled device -> host (eviction "
            "turned demotion)")
        self.kv_promotions = counter(
            "paddle_tpu_kv_tier_promotions_total",
            "demoted KV pages restored host -> device after their "
            "checksum verified")
        self.kv_tier_hits = counter(
            "paddle_tpu_kv_tier_hits_total",
            "admission lookups whose hash chain reached host-tier "
            "content (the hit that triggers an async promote-back)")
        self.kv_drops = counter(
            "paddle_tpu_kv_tier_drops_total",
            "demoted blocks lost: host slab full, or a promotion "
            "failed its demotion-time digest (invalidate + recompute)")
        self.kv_tier_pages = gauge(
            "paddle_tpu_kv_tier_pages",
            "prefix-cache pages resident per tier (hbm = spliceable "
            "device pages, host = spilled slab rows)",
            labelnames=("tier",))
        self.kv_promote_seconds = histogram(
            "paddle_tpu_kv_tier_promote_seconds",
            "hash-chain hit on a demoted page to its verified bytes "
            "landing back in the device pool")
        # multi-step scheduling surface (ISSUE 12): how many engine
        # iterations each host round trip actually batched (1 = classic
        # stepping; N = the multi-step fast path engaged at depth N)
        self.steps_per_roundtrip = histogram(
            "paddle_tpu_engine_steps_per_roundtrip",
            "engine iterations batched behind one host round trip "
            "(multi-step scheduling; 1 = classic per-iteration stepping)",
            buckets=SIZE_BUCKETS)
        # per-depth counter children cached here: .labels() costs a
        # tuple build + dict probe per call, and step() hits one depth
        # every iteration
        self._depth_children: Dict[int, object] = {}
        # per-tenant histogram/counter children, same rationale; the
        # seen-set bounds label cardinality (a hostile client cycling
        # tenant strings must not grow the scrape unboundedly)
        self._tenant_seen: set = set()
        self._ttft_children: Dict[str, object] = {}
        self._qwait_children: Dict[str, object] = {}
        # TTFT-component children: four fixed labels, cached eagerly
        self._component_children: Dict[str, object] = {
            c: self.ttft_component.labels(component=c)
            for c in ("placement", "queue_wait", "promote_wait",
                      "prefill")}

    _TENANT_CAP = 24  # distinct tenant label values before "other"
    _EXPERT_CAP = 32  # distinct expert label values before "other"

    def moe_expert_at(self, e: int):
        child = self._moe_expert_children.get(e)
        if child is None:
            label = str(e) if e < self._EXPERT_CAP else "other"
            child = self.moe_expert_tokens.labels(expert=label)
            self._moe_expert_children[e] = child
        return child

    def chain_depth_at(self, k: int):
        child = self._depth_children.get(k)
        if child is None:
            child = self.chain_depth.labels(depth=k)
            self._depth_children[k] = child
        return child

    def _tenant_label(self, tenant: str) -> str:
        t = tenant or "default"
        if t not in self._tenant_seen:
            if len(self._tenant_seen) >= self._TENANT_CAP:
                return "other"
            self._tenant_seen.add(t)
        return t

    def ttft_for(self, tenant: str):
        t = self._tenant_label(tenant)
        child = self._ttft_children.get(t)
        if child is None:
            child = self.ttft.labels(tenant=t)
            self._ttft_children[t] = child
        return child

    def queue_wait_for(self, tenant: str):
        t = self._tenant_label(tenant)
        child = self._qwait_children.get(t)
        if child is None:
            child = self.queue_wait.labels(tenant=t)
            self._qwait_children[t] = child
        return child

    def on_harvest(self, req: Request, fresh: int):
        """Per-request token-latency accounting; called once per harvest
        with the number of fresh tokens DELIVERED — never an assumed
        per-step constant. A vanilla chained step lands k*chunk_size
        tokens, a spec verify step lands 1..spec_k+1 depending on
        acceptance (ISSUE 5 satellite): both normalize the harvest span
        by the accepted count, so the TPOT histogram stays a true
        per-token latency while acceptance varies. (The chain-depth
        maximizer's dispatch-cost EMA is likewise acceptance-proof: it
        only samples pure-decode CHAIN steps — _observe_chain_time —
        which spec steps never feed.)"""
        now = time.perf_counter()
        if req._t_first is None:
            req._t_first = now
            self.ttft_for(req.tenant).observe(now - req._t_arrival)
            self._on_first_token(req, now)
            if fresh > 1:
                # a chained harvest delivers first token + decode tokens
                # at once; attribute the span evenly to the decode tokens
                self.tpot.observe((now - req._t_arrival) / fresh)
        elif req._t_last is not None and fresh:
            self.tpot.observe((now - req._t_last) / fresh)
        req._t_last = now
        self.tokens.inc(fresh)

    def _on_first_token(self, req: Request, now: float):
        """TTFT latency attribution (ISSUE 18), emitted once at first
        harvest: the four components partition [submit, first-token] on
        the perf_counter clock — placement = submit→arrival, queue_wait
        = arrival→admit minus the promote wait spent inside the
        admission splice, promote_wait = that wait, prefill =
        admit→first-token — so their sum IS the TTFT (float error
        only). Observed into the labeled histogram always; laid down as
        retroactive child spans when the request carries a trace."""
        base = req._t_submit if req._t_submit is not None \
            else req._t_arrival
        admit = req._t_admit if req._t_admit is not None \
            else req._t_arrival
        promote = req._t_promote_wait
        comps = (
            ("placement", base, req._t_arrival - base),
            ("queue_wait", req._t_arrival,
             (admit - req._t_arrival) - promote),
            ("promote_wait", admit - promote, promote),
            ("prefill", admit, now - admit),
        )
        for cname, _, dur in comps:
            self._component_children[cname].observe(max(0.0, dur))
        if _TRACER.enabled and req.trace is not None:
            wall = time.time()
            for cname, t0, dur in comps:
                _TRACER.complete(f"ttft.{cname}", "ttft",
                                 wall - (now - t0), dur,
                                 parent=req.trace, rid=req.rid)
            _TRACER.complete("ttft", "ttft", wall - (now - base),
                             now - base, parent=req.trace,
                             rid=req.rid, tenant=req.tenant)


class Engine:
    """Continuous-batching engine; see module docstring."""

    def __init__(self, model, max_slots=8, num_pages=512, page_size=16,
                 chunk_size=16, eos_id: Optional[int] = None,
                 dtype=jnp.bfloat16, quantized_cache=False, max_chain=8,
                 top_k: Optional[int] = None, metrics: bool = True,
                 spec: Optional[str] = None, spec_k: int = 4,
                 draft_model=None, max_queue: Optional[int] = None,
                 deadline_s: Optional[float] = None, max_retries: int = 8,
                 fault_plan=None, watchdog: Optional[dict] = None,
                 prefix_cache: bool = False, kv_host_pages: int = 0,
                 prefill_chunk: Optional[int] = None,
                 tp: Optional[int] = None, ep: Optional[int] = None,
                 capacity_factor: Optional[float] = None,
                 disaggregate: bool = False,
                 multi_step: int = 1, integrity=None):
        cfg = model.config
        self.model = model
        self.cfg = cfg
        self.max_slots = max_slots
        self.page_size = page_size
        self.chunk_size = chunk_size
        self.dtype = dtype
        self.max_chain = max(1, int(max_chain))
        if top_k is not None and not 1 <= top_k <= cfg.vocab_size:
            # fail here, not as an opaque trace-time lax.top_k error at
            # the first sampled request (code-review r4)
            raise ValueError(
                f"top_k={top_k} must be in [1, vocab_size="
                f"{cfg.vocab_size}]")
        self.top_k = top_k
        self.eos_id = eos_id
        self.quantized = bool(quantized_cache)
        self.max_pages_per_seq = cfg.max_position // page_size
        self.num_pages = num_pages
        # expert-parallel MoE serving (ISSUE 17): an MoE config grows
        # every compiled program ONE trailing router-stats output
        # (per-expert kept counts, capacity drops, entropy — see
        # models.llama.moe_stats_size); _moe_pending holds undrained
        # device handles, _moe_tot the cumulative host aggregate.
        n_exp = int(getattr(cfg, "num_experts", 0) or 0)
        self._moe_stats_n = (n_exp + 3) if n_exp else 0
        self._moe_pending: List = []
        self._moe_tot = np.zeros((self._moe_stats_n,), np.float64)
        if capacity_factor is not None:
            if not n_exp:
                raise ValueError(
                    "capacity_factor= on a dense model: the capacity "
                    "factor sizes each expert's token buffer — serve an "
                    "MoE config or drop the knob")
            cf = float(capacity_factor)
            if cf <= 0:
                raise ValueError(
                    f"capacity_factor={cf} must be > 0 (it scales the "
                    "per-expert token capacity ceil(cf*k*T/E))")
            # host-side override BEFORE any trace: capacity is a static
            # shape input, so changing it later would silently recompile
            for lyr in model.sublayers(include_self=True):
                if hasattr(lyr, "router") and hasattr(lyr, "experts_gate"):
                    lyr.capacity_factor = cf
        # model-runner (ISSUE 11 tentpole): owns the compiled programs
        # and — at tp>1 / ep>1 — the mesh they trace under (weights
        # column/row-sharded over tp, stacked expert weights sharded
        # over ep, KV pool head-sharded, host operands replicated; one
        # shard_map per dispatch). The scheduler below stays
        # device-count-agnostic.
        from .runner import ModelRunner

        self.runner = ModelRunner(self, tp, ep)
        # compiled-program shapes quantize to this (watchdog batch
        # shrink must keep slot caps mesh-aligned — ISSUE 11 satellite)
        self._batch_quantum = self.runner.tp if self.runner.sharded else 1
        # chunked prefill (ISSUE 9): prompts stream into the cache
        # prefill_chunk tokens per mixed step instead of one bucketed
        # prefill dispatch; _chunk_left maps a mid-prefill slot to the
        # prompt tokens not yet written
        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if not 2 <= prefill_chunk <= cfg.max_position:
                # 1-wide slabs would hit the reference's GEMV path and
                # one chunk per token is a pathological schedule anyway;
                # fail at construction, not mid-serve
                raise ValueError(
                    f"prefill_chunk={prefill_chunk} must be in "
                    f"[2, max_position={cfg.max_position}]")
        self.prefill_chunk = prefill_chunk
        # prefill/decode role disaggregation (ISSUE 11): prefill-role
        # slots stream chunks through the mixed program while
        # decode-role slots ride deep chains in the SAME scheduling
        # step, pages handed over through the cache-coordinator
        self.disaggregate = bool(disaggregate)
        if self.disaggregate and prefill_chunk is None:
            raise ValueError(
                "disaggregate=True requires prefill_chunk (prefill-role "
                "steps stream prompts chunk-by-chunk)")
        self._chunk_left: Dict[int, np.ndarray] = {}
        # cache-coordinator (ISSUE 11 tentpole): the paged pool +
        # allocator + prefix cache. Page tables and refcounts stay
        # host-global (PR 8's COW logic untouched); the device buffers
        # partition across the TP axis when the runner is sharded.
        # kv_host_pages > 0 (ISSUE 15) arms the host-DRAM spill tier
        # below the pool: idle cached pages demote asynchronously
        # instead of evicting, and hash-chain hits on demoted pages
        # promote back checksum-verified — 0 (the default) builds no
        # tier, no worker thread, and byte-identical scheduling.
        from .cache_coord import CacheCoordinator

        self._cache = CacheCoordinator(self, prefix_cache=prefix_cache,
                                       kv_host_pages=kv_host_pages)
        self._queue: List[Request] = []
        self._active: Dict[int, Request] = {}  # slot -> request
        self._last_tok = np.zeros((max_slots,), np.int32)
        self._temps = np.zeros((max_slots,), np.float32)
        self._keys = np.zeros((max_slots, 2), np.uint32)
        self._next_rid = 0
        # multi-step scheduling (ISSUE 12): default iterations batched
        # per host round trip when step() is called without n; the fast
        # path only engages where streams provably stay bit-identical
        # (see _multi_chained_step)
        self.multi_step = max(1, int(multi_step))
        self._chain_time_ema = {}   # depth k -> EMA step wall seconds
        self._chain_obs = 0          # pure-decode steps observed
        self._probe_budget = 2       # bounded depth-calibration probes
        self._dispatch_ratio = None  # measured boundary cost, chunk units
        # serving state that must travel as jit ARGUMENTS: parameters
        # plus buffers (a weight-only-quantized model keeps its int8/int4
        # weights + scales as buffers; baking them in as jit constants
        # would bloat every compiled bucket by the full weight bytes)
        self._swap = [p for _, p in model.named_parameters()]
        self._swap += [b for _, b in model.named_buffers()
                       if b is not None]
        # placed ONCE on the runner's mesh (column/row shards at tp>1),
        # so no dispatch ever re-shards the weights
        self._params = self.runner.place_params(
            [t._data for t in self._swap])
        # process-global serving telemetry; metrics=False drops every
        # record site to a single None check (the microbenchmarked
        # baseline for the <1% overhead budget, tools/mb_metrics.py)
        self._m = _EngineMetrics() if metrics else None
        if self._m is not None:
            self._m.pages_total.set(num_pages - 1)  # page 0 is trash
        # speculative decoding (ISSUE 5): spec="ngram" (model-free prompt
        # lookup) or "draft" (small draft LM, pass draft_model=); the
        # scheduling loop swaps the chained decode for drafter→verify
        # steps landing 1..spec_k+1 tokens each — see _spec_step
        self._spec = None
        if spec not in (None, "off"):
            from .spec import SpecDecoder

            self._spec = SpecDecoder(self, mode=spec, k=spec_k,
                                     draft_model=draft_model)
        # ---- fault tolerance (ISSUE 6) --------------------------------
        self.max_queue = max_queue
        self.deadline_s = deadline_s
        self.max_retries = int(max_retries)
        self._has_deadlines = deadline_s is not None
        self._stall_steps = 0  # consecutive queued-but-unadmittable steps
        self._pending_inflight = []  # pre-admissions the current step owns
        # promote wait measured by the most recent _splice_prefix — the
        # admission loop attributes it to the request it spliced for
        # (TTFT decomposition, ISSUE 18)
        self._last_promote_wait_s = 0.0
        # deterministic fault injection: explicit plan/spec wins, else the
        # FLAGS_fault_inject / PADDLE_TPU_FAULT_INJECT flag
        self._fi = (FaultPlan.from_spec(fault_plan)
                    if fault_plan is not None else plan_from_flags())
        # the watchdog owns _spec_enabled and _slot_cap (degraded-mode
        # state machine: spec→vanilla, then admission cap halved, with
        # recovery probing); kwargs tune its thresholds
        self._spec_enabled = True
        self._slot_cap = max_slots
        self._watchdog = Watchdog(self, **(watchdog or {}))
        # ---- data-integrity sentinel (ISSUE 14) -----------------------
        # integrity="audit"|"strict"|dict|IntegrityConfig arms online
        # SDC audits: load-time weight digests with periodic idle-step
        # shard probes, per-page KV checksums verified at splice and
        # re-registration, and (strict) an every-N-steps shadow
        # recompute of one greedy row through the contiguous twin.
        # Constructed LAST: the weight baseline digests the freshly
        # placed _params, and the cache-coordinator's alloc hooks read
        # the attribute via getattr (it does not exist during the
        # coordinator's own construction above).
        from .integrity import IntegritySentinel

        self._integrity = IntegritySentinel.build(self, integrity)

    # --------------------------------------------- engine-core delegation
    # The tentpole split (ISSUE 11) moved pool/allocator state into the
    # cache-coordinator and program caches into the model-runner; the
    # scheduler (and its tests) keep reading them through these
    # delegators, so PR 6-9's host logic runs textually unchanged.
    @property
    def tables(self):
        return self._cache.tables

    @property
    def lengths(self):
        return self._cache.lengths

    @property
    def _page_ref(self):
        return self._cache.page_ref

    @property
    def _pcache(self):
        return self._cache.pcache

    @property
    def kv_tier(self):
        """The host-DRAM spill tier (ISSUE 15), or None when
        ``kv_host_pages`` was 0."""
        return self._cache.tier

    @property
    def _cow_pending(self):
        return self._cache.cow_pending

    @_cow_pending.setter
    def _cow_pending(self, v):
        self._cache.cow_pending = v

    @property
    def _free_pages(self):
        return self._cache.free_pages

    @_free_pages.setter
    def _free_pages(self, v):
        self._cache.free_pages = v

    @property
    def _free_slots(self):
        return self._cache.free_slots

    @_free_slots.setter
    def _free_slots(self, v):
        self._cache.free_slots = v

    @property
    def k_pages(self):
        return self._cache.k_pages

    @k_pages.setter
    def k_pages(self, v):
        self._cache.k_pages = v

    @property
    def v_pages(self):
        return self._cache.v_pages

    @v_pages.setter
    def v_pages(self, v):
        self._cache.v_pages = v

    @property
    def scale_pages(self):
        return self._cache.scale_pages

    @scale_pages.setter
    def scale_pages(self, v):
        self._cache.scale_pages = v

    @property
    def _decode_fns(self):
        return self.runner.decode_fns

    @property
    def _prefill_fns(self):
        return self.runner.prefill_fns

    @property
    def _mixed_fns(self):
        return self.runner.mixed_fns

    # ------------------------------------------------------------- requests
    def _reject(self, exc):
        """Reject-at-submission: count it and raise the taxonomy error
        (all admission-time classes also subclass ValueError)."""
        if self._m is not None:
            self._m.admission_rejected.inc()
        raise exc

    def add_request(self, prompt, max_new_tokens, on_token=None,
                    temperature=0.0, seed=None,
                    deadline_s: Optional[float] = None,
                    tenant: Optional[str] = None,
                    resume_tokens=None, trace=None,
                    t_submit: Optional[float] = None) -> Request:
        """Submit a request. EVERY way the request could be unservable is
        checked here, up front (ISSUE 6 satellite): malformed input →
        ``ValidationError``, a sequence the pool/table geometry can never
        hold → ``AdmissionRejected``, bounded-queue backpressure →
        ``QueueFull``. Nothing about a single request can fail mid-step
        for a reason that was knowable at submission.

        ``resume_tokens`` (ISSUE 13) is the resume-from-emitted admission
        path for replica failover: tokens this stream ALREADY emitted on
        a replica that died. They count against ``max_new_tokens`` but
        are never re-delivered through ``on_token`` — admission
        re-prefills prompt‖emitted (the preemption machinery's
        ``_prefix``, with the prefix cache absorbing the recompute) and
        generation continues bit-identically where the dead replica
        stopped. Seeded-sampled streams reconstruct their key state by
        replaying one key split per emitted token
        (``_advance_sample_key``); that replay is exact for the vanilla
        and chunked paths but not under spec decode (fixed k+2 burns per
        verify STEP), so a sampled resume on a spec-enabled engine is
        rejected up front rather than silently diverging."""
        raw = np.asarray(prompt)
        if raw.dtype.kind not in "iu":
            self._reject(ValidationError(
                f"prompt must be integer token ids, got dtype {raw.dtype}"))
        prompt = raw.astype(np.int32).reshape(-1)
        if prompt.size == 0:
            self._reject(ValidationError("empty prompt"))
        if int(prompt.min()) < 0 or int(prompt.max()) >= self.cfg.vocab_size:
            self._reject(ValidationError(
                f"prompt token ids must lie in [0, {self.cfg.vocab_size}); "
                f"got range [{int(prompt.min())}, {int(prompt.max())}]"))
        if int(max_new_tokens) <= 0:
            self._reject(ValidationError(
                f"max_new_tokens must be positive, got {max_new_tokens}"))
        if float(temperature) < 0.0:
            self._reject(ValidationError(
                f"temperature must be >= 0, got {temperature}"))
        # keep one chunk of headroom below max_position; NOTE this does
        # not bound chain overshoot (up to max_chain*chunk_size) — the
        # cache-write path's length cap and positions() clamp are the
        # actual out-of-bounds safety mechanism for overshooting
        # stragglers, this limit just keeps USEFUL tokens in range
        limit = self.cfg.max_position - self.chunk_size - 1
        if prompt.size + max_new_tokens > limit:
            clamped = max(0, limit - prompt.size)
            if clamped == 0:
                # a silent zero-token "completion" would mis-diagnose as an
                # engine bug downstream (ADVICE r3) — fail fast instead
                self._reject(ValidationError(
                    f"prompt ({prompt.size}) leaves no room to generate: "
                    f"prompt + generation must stay under max_position - "
                    f"chunk_size ({limit})"))
            import warnings

            warnings.warn(
                f"max_new_tokens clamped {max_new_tokens} -> {clamped}: "
                f"prompt ({prompt.size}) + generation must stay under "
                f"max_position - chunk_size ({limit})", RuntimeWarning,
                stacklevel=2)
            max_new_tokens = clamped
        # fail fast on a request that could NEVER be served — otherwise the
        # scheduler would spin forever waiting for pages that cannot exist
        worst = self._pages_needed(prompt.size + max_new_tokens
                                   + self.chunk_size)
        if worst > min(self.max_pages_per_seq, self.num_pages - 1):
            self._reject(AdmissionRejected(
                f"request needs up to {worst} pages but the pool/table caps "
                f"at {min(self.max_pages_per_seq, self.num_pages - 1)} — "
                "grow num_pages or shrink the request"))
        # bounded wait queue (backpressure): refuse to buffer unboundedly
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            self._reject(QueueFull(
                f"wait queue full ({len(self._queue)}/{self.max_queue}); "
                "retry later or raise max_queue"))
        resumed: List[int] = []
        if resume_tokens is not None and len(resume_tokens):
            raw_r = np.asarray(resume_tokens)
            if raw_r.dtype.kind not in "iu":
                self._reject(ValidationError(
                    f"resume_tokens must be integer token ids, got dtype "
                    f"{raw_r.dtype}"))
            resumed = [int(t) for t in raw_r.reshape(-1)]
            if min(resumed) < 0 or max(resumed) >= self.cfg.vocab_size:
                self._reject(ValidationError(
                    f"resume_tokens must lie in [0, {self.cfg.vocab_size})"))
            if len(resumed) >= int(max_new_tokens):
                self._reject(ValidationError(
                    f"resume_tokens ({len(resumed)}) already meet the "
                    f"generation budget ({max_new_tokens}) — the stream "
                    "is complete, nothing to resume"))
            if self.eos_id is not None and self.eos_id in resumed:
                self._reject(ValidationError(
                    "resume_tokens contain eos — the stream already "
                    "terminated on its source replica"))
            if float(temperature) > 0.0 and self._spec is not None:
                self._reject(ValidationError(
                    "sampled resume on a spec-enabled engine: spec "
                    "decode burns keys per verify step, not per token, "
                    "so the migrated key state cannot be reconstructed "
                    "from the emitted-token count — resume on a "
                    "spec=off replica (greedy resumes are exact either "
                    "way)"))
            if float(temperature) > 0.0 and seed is None:
                self._reject(ValidationError(
                    "sampled resume needs an explicit seed: the source "
                    "replica's implicit per-rid seed does not transfer "
                    "across engines"))
        req = Request(self._next_rid, prompt, max_new_tokens, on_token,
                      temperature=float(temperature), seed=seed,
                      tenant=str(tenant) if tenant else "default")
        if resumed:
            # pre-populate emitted history: _prefix() re-prefills
            # prompt‖emitted exactly like a preemption re-admission, and
            # _harvest appends (and delivers) only FRESH tokens
            req.tokens = resumed
            if float(temperature) > 0.0:
                seed_v = int(seed if seed is not None else req.rid)
                key0 = np.array(
                    [(seed_v >> 32) & 0xFFFFFFFF, seed_v & 0xFFFFFFFF],
                    np.uint32)
                req._key = np.asarray(jax.device_get(_advance_sample_key(
                    jnp.asarray(key0), jnp.int32(len(resumed)))),
                    np.uint32)
        req._t_arrival = time.perf_counter()
        if _TRACER.enabled:
            # ISSUE 18: carry the upstream span context (wire string)
            # so engine spans/instants land in the caller's trace, and
            # the upstream submit time so the TTFT decomposition's
            # placement component spans submit -> engine arrival
            req.trace = trace if isinstance(trace, str) and trace else None
            if t_submit is not None:
                req._t_submit = float(t_submit)
            _TRACER.instant("engine.enqueue", "engine",
                            parent=req.trace, rid=req.rid,
                            prompt_len=int(prompt.size),
                            queue_depth=len(self._queue))
        ttl = deadline_s if deadline_s is not None else self.deadline_s
        if ttl is not None:
            req.deadline = req._t_arrival + float(ttl)
            self._has_deadlines = True
        self._next_rid += 1
        self._queue.append(req)
        if self._cache.tier is not None:
            # promote PREFETCH (ISSUE 15): peek the hash chain now so a
            # demoted prefix starts its host->device copy while the
            # request waits in the queue — by admission the promoted
            # pages splice like ordinary cached ones. Pure peek: no LRU
            # re-stamp, no hit/miss accounting (the splice-time lookup
            # owns those), and a promote that hasn't landed by then
            # simply degrades this admission to a partial-prefill miss.
            _, _, demoted = self._pcache.lookup(self._prefix(req),
                                                touch=False, tiers=True)
            if demoted:
                self._cache.tier.request_promote(demoted)
        if self._m is not None:
            self._m.requests.inc()
        return req

    def cancel(self, rid: int) -> bool:
        """Host-side cancellation: fail the request (terminal FAILED,
        reason ``cancelled``) wherever it lives — queued or mid-decode —
        recycling its slot and pages immediately. Returns False when the
        id is unknown or the request already reached a terminal state."""
        for req in list(self._active.values()) + list(self._queue):
            if req.rid == rid and not req.done:
                self._fail_request(req, CancelledError(
                    f"request {rid} cancelled by caller", rid=rid))
                return True
        return False

    def _fail_request(self, req: Request, exc: BaseException):
        """Move ONE request to terminal FAILED: record the taxonomy
        reason, recycle its slot/pages, drop it from the queue — and
        leave every other request untouched. The single choke point all
        per-request failure paths funnel through."""
        if req.done:
            return
        req.failure = exc
        req.failure_reason = failure_reason(exc)
        req.done = True
        if req.slot is not None:
            self._active.pop(req.slot, None)
            self._free_slot(req.slot)
            req.slot = None
        if req in self._queue:
            self._queue.remove(req)
        if self._spec is not None:
            self._spec.controller.forget(req)
        if self._m is not None:
            self._m.failures.labels(
                reason=req.failure_reason,
                tenant=self._m._tenant_label(req.tenant)).inc()

    def _expire_deadlines(self):
        """Fail every queued/active request whose deadline/TTL elapsed
        (reason ``deadline``). Runs at the top of each scheduling step —
        a deadline is enforced at step granularity, the engine's only
        host-visible clock edge."""
        now = time.perf_counter()
        for req in list(self._active.values()) + list(self._queue):
            if req.deadline is not None and now > req.deadline \
                    and not req.done:
                self._fail_request(req, DeadlineExceeded(
                    f"request {req.rid} exceeded its deadline "
                    f"({now - req._t_arrival:.3f}s since arrival)",
                    rid=req.rid))

    def _note_stall(self):
        """Queued requests, nothing active, no admission possible. The
        pre-ISSUE-6 behavior was a hard RuntimeError; now the engine
        tolerates a couple of steps (deadline expiry or recovery may
        free pages), then sheds the queue head with ``PoolExhausted`` —
        forward progress without crashing the batch that isn't there."""
        self._stall_steps += 1
        if self._stall_steps >= 3 and self._queue:
            self._stall_steps = 0
            head = self._queue[0]
            self._fail_request(head, PoolExhausted(
                f"scheduler stalled: page pool too fragmented/small to "
                f"admit request {head.rid}", rid=head.rid))

    @staticmethod
    def _wrap_step_fault(exc: BaseException, req: Request) -> StepFault:
        err = StepFault(f"{type(exc).__name__}: {exc}", rid=req.rid)
        err.__cause__ = exc
        return err

    # ------------------------------------------------------------ allocator
    def _pages_needed(self, length):
        return (int(length) + self.page_size - 1) // self.page_size

    def _alloc_page(self) -> Optional[int]:
        """Claim one physical page — see CacheCoordinator.alloc_page
        (free list first, then LRU eviction of an idle cached page)."""
        return self._cache.alloc_page()

    def _release_page(self, page):
        """Drop one page reference — see CacheCoordinator.release_page
        (the single release choke point; shared pages never double-free)."""
        self._cache.release_page(page)

    def _available_pages(self) -> int:
        """Pages an allocation burst could claim (free + idle cached)."""
        return self._cache.available_pages()

    def _ensure_pages(self, slot, new_len):
        need = self._pages_needed(new_len)
        # count actual allocations (chain headroom can exceed
        # pages_needed(length); recomputing from length would overwrite —
        # and leak — last round's headroom pages)
        have = int(np.count_nonzero(self.tables[slot]))
        if need > self.max_pages_per_seq:
            # taxonomy, not RuntimeError: callers fail the REQUEST
            # (add_request's up-front check makes this unreachable for
            # well-formed traffic, so hitting it is an engine bug — but
            # an engine bug one request wide, not batch wide)
            raise PoolExhausted(
                f"sequence needs {need} pages but the per-sequence table "
                f"caps at {self.max_pages_per_seq}")
        if need > have and self._fi is not None \
                and self._fi.fire("pool-exhaustion"):
            # injected exhaustion only when a real allocation would
            # happen — a no-op ensure succeeds even over an empty pool
            return False
        taken = []
        for i in range(have, need):
            page = self._alloc_page()
            if page is None:
                # roll back the partial allocation — a False return must
                # leave the allocator unchanged or the pages leak
                for j in range(have, have + len(taken)):
                    self.tables[slot, j] = 0
                for pg in reversed(taken):
                    self._release_page(pg)
                return False
            taken.append(page)
            self.tables[slot, i] = page
        return True

    def _trim_pages(self, slot, keep_len):
        """Release a slot's headroom pages beyond ``keep_len`` (headroom
        pages are empty by construction — data only exists up to
        ``lengths[slot]``). Refcount-aware: a spliced shared page merely
        loses this slot's reference (callers only ever trim back to at
        least the prefilled prefix, so shared pages stay in range — the
        release path is the safety net, not the common case)."""
        need = self._pages_needed(keep_len)
        have = int(np.count_nonzero(self.tables[slot]))
        for i in range(have - 1, need - 1, -1):
            self._release_page(int(self.tables[slot, i]))
            self.tables[slot, i] = 0

    # --------------------------------------------------- prefix cache (ISSUE 8)
    def _splice_prefix(self, row, prefix) -> int:
        """Prefix-cache admission: splice the cached block-aligned prefix
        of ``prefix`` into the (fresh, all-zero) table ``row`` — refcount++
        per shared page — and return the token count the prefill may skip.

        Copy-on-write at divergence: a FULL-prefix match still needs the
        last prompt token recomputed (its logits produce the first
        generated token), and that token's KV write lands inside the final
        matched page — which is shared. The page is copied to a fresh one
        (device copies batch per wave in ``_prefill_wave``) and the splice
        reports ``prefix.size - 1`` cached tokens, so the write — and
        every decode append after it — only ever touches pages this slot
        owns. Partial matches divide at a page boundary by construction
        (only full blocks are cached), so their suffix writes open fresh
        pages and need no copy.

        The ``prefix-cache-corruption`` fault point fires here: a doubted
        page gets its device bytes flipped (when idle — an in-use page is
        never corrupted by the harness), the cache invalidates it and
        every descendant block, and THIS admission recomputes from scratch
        — corruption costs a miss, never a wrong token."""
        self._last_promote_wait_s = 0.0
        if self._pcache is None:
            return 0
        if self._cache.tier is not None:
            # tiered splice (ISSUE 15): peek the chain, start promotions
            # for any demoted continuation (usually already in flight —
            # add_request prefetched them while the request queued), and
            # give in-flight ones a BOUNDED drain-wait far below the
            # recompute they would otherwise cost. Whatever landed
            # splices below like ordinary cached pages; whatever is
            # still in flight rides partial prefill — a slow promote
            # degrades to a miss, never a stall or a wrong token.
            tier = self._cache.tier
            _, _, demoted = self._pcache.lookup(prefix, touch=False,
                                                tiers=True)
            if demoted:
                tier.request_promote(demoted)
                t0 = time.perf_counter()
                tier.await_promotions(demoted)
                # attributed to the admitting request's promote_wait
                # TTFT component by _admit_dispatch/_bind_chunked
                self._last_promote_wait_s = time.perf_counter() - t0
                if _TRACER.enabled:
                    _TRACER.instant(
                        "kvtier.promote_wait", "cache",
                        waited_s=self._last_promote_wait_s,
                        pages=len(demoted))
            pages, matched, _ = self._pcache.lookup(prefix, tiers=True)
        else:
            pages, matched = self._pcache.lookup(prefix)
        if matched and self._fi is not None \
                and self._fi.fire("prefix-cache-corruption"):
            doubted = pages[-1]
            if int(self._page_ref[doubted]) == 0:
                self._corrupt_page(doubted)
            for p in self._pcache.invalidate_page(doubted):
                if int(self._page_ref[p]) == 0:
                    self._free_pages.append(p)
            pages, matched = [], 0  # invalidate-on-doubt: recompute all
            # the lookup scored a hit before doubt struck; the admission
            # is in fact a miss — keep the cache's own tallies consistent
            # with the prometheus counters below
            self._pcache.hits -= 1
            self._pcache.misses += 1
        if matched and self._fi is not None \
                and self._fi.fire("bit-flip-kv"):
            # SILENT corruption (ISSUE 14): flip a matched idle page's
            # device bytes with NO doubt signal — unlike the
            # prefix-cache-corruption point above, nothing invalidates,
            # so only the checksum probe below stands between this flip
            # and a wrong token
            doomed = pages[-1]
            if int(self._page_ref[doomed]) == 0:
                self._corrupt_page(doomed)
        if matched and self._integrity is not None:
            # close the PR 8 trust window: the token re-verify in
            # PrefixCache.lookup proves the ENTRY matches the prompt,
            # but said nothing about the page BYTES between
            # registration and this splice — the checksum probe does
            bad = self._integrity.verify_pages(pages)
            if bad:
                self._contain_kv_corruption(bad)
                pages, matched = [], 0
                self._pcache.hits -= 1
                self._pcache.misses += 1
        if self._m is not None:
            (self._m.pc_hits if matched else self._m.pc_misses).inc()
        if _TRACER.enabled:
            _TRACER.instant("cache.prefix_lookup", "cache",
                            matched=int(matched),
                            prefix_len=int(prefix.size))
        if not matched:
            return 0
        cow = None
        if matched == int(prefix.size):
            cow = self._alloc_page()
            if cow is None:
                # no page for the copy under extreme pressure: fall back
                # to recomputing the whole last block instead
                pages = pages[:-1]
                matched -= self.page_size
                if not matched:
                    return 0
        for i, p in enumerate(pages if cow is None else pages[:-1]):
            row[i] = p
            self._page_ref[p] += 1
        if cow is not None:
            self._cow_pending.append((int(pages[-1]), int(cow)))
            row[len(pages) - 1] = cow
            matched -= 1  # the recomputed final token
        if self._m is not None:
            self._m.pc_cached_tokens.inc(matched)
        return matched

    def _corrupt_page(self, page):
        """The ``prefix-cache-corruption`` fault point's actual damage:
        garbage layer-0 K rows for one cached page. Safe to leave behind
        because a page is only ever read below ``lengths`` — rows the
        next owner rewrites during its own prefill/decode before they
        become visible — so with the invalidate-on-doubt path routing
        lookups around it, the flip can cost a miss but never a token."""
        self._cache.corrupt_page(page)

    def _register_prefix(self, prefix, row):
        """Publish the freshly prefilled FULL pages of ``prefix`` into the
        cache (content-addressed by block-chain hash). Pages stay owned by
        the slot/row; once released they stay resident at refcount 0 until
        LRU eviction reclaims them. Blocks already cached keep their
        original page (the COW copy, in particular, stays private — its
        final row diverges the moment decode appends into it).

        With the integrity sentinel armed (ISSUE 14) every page now
        backing these blocks gets a checksum: fresh pages record their
        baseline, and an already-cached block's page — possibly parked
        at refcount 0 since its first registration — is RE-verified, so
        corruption of an idle page is caught at the earliest touch."""
        if self._pcache is None:
            return
        full = int(prefix.size) // self.page_size
        if full:
            blocks = prefix[:full * self.page_size]
            self._pcache.register(
                blocks, [int(row[i]) for i in range(full)])
            if self._integrity is not None:
                # the canonical backing pages (dedup may differ from
                # this row's private pages): peek, never re-stamp
                pages, _ = self._pcache.lookup(blocks, touch=False)
                bad = self._integrity.note_registered(pages)
                if bad:
                    self._contain_kv_corruption(bad)

    def adopt_kv_pages(self, payload) -> int:
        """Decode-side adoption of a cross-replica KV handoff payload
        (ISSUE 20): digest-verify the shipped page rows, restore them
        into freshly allocated pool pages, and publish them in the
        prefix cache so the next admission of the same prompt splices
        instead of recomputing. Engine thread (the cluster reaches it
        through ``ServingFrontend.call``). Returns the number of pages
        adopted; 0 on any mismatch/pressure — the caller's fallback is
        plain resume-from-emitted recompute, so a bad payload costs a
        cache miss, never a stall or a wrong token.

        Verification truncates at the FIRST digest mismatch: chain keys
        commit to the whole prefix, so a clean prefix of the shipment
        is still independently trustworthy. Blocks the local cache
        already holds HBM-resident are skipped (first-writer-wins, same
        as ``PrefixCache.register``); a shipped block whose entry is
        host-tier re-binds to the restored page (recompute-as-promote,
        minus the recompute)."""
        if self._pcache is None or not payload:
            return 0
        pc = self._pcache
        if int(payload.get("page_size", -1)) != self.page_size:
            return 0
        tokens = np.asarray(payload.get("tokens", ()), np.int32)
        rows_per_page = payload.get("pages") or []
        digests = payload.get("digests") or []
        dev_sums = payload.get("dev_sums") or [None] * len(rows_per_page)
        n_blocks = min(tokens.size // self.page_size,
                       len(rows_per_page), len(digests))
        good = 0
        for j in range(n_blocks):
            d = hashlib.blake2b(digest_size=16)
            for a in rows_per_page[j]:
                d.update(np.ascontiguousarray(a).tobytes())
            if d.hexdigest() != digests[j]:
                break  # later blocks chain through this one: truncate
            good += 1
        from .integrity import count_integrity_check

        count_integrity_check("kv_handoff", good == n_blocks)
        if not good:
            return 0
        # skip what is already resident (peek, no stamp/accounting) —
        # re-restoring an identical block would only burn a page
        _, matched = pc.lookup(tokens[:good * self.page_size],
                               touch=False)
        start = matched // self.page_size
        fresh = []  # (block_index, page)
        for j in range(start, good):
            page = self._cache.alloc_page()
            if page is None:
                break  # pool pressure: adopt the prefix that fits
            fresh.append((j, int(page)))
        if not fresh:
            return 0
        import jax.numpy as jnp

        w = 32  # fixed-width restore waves (HostTier.COPY_WIDTH idiom)
        for off in range(0, len(fresh), w):
            chunk = fresh[off:off + w]
            m = len(chunk)
            idx = np.zeros((w,), np.int32)
            idx[:m] = [p for _, p in chunk]
            stacked = [
                np.stack([np.asarray(rows_per_page[j][i])
                          for j, _ in chunk]
                         + [np.zeros_like(
                             np.asarray(rows_per_page[chunk[0][0]][i]))]
                         * (w - m))
                for i in range(len(rows_per_page[chunk[0][0]]))
            ]
            self._cache.set_pages(self.runner.restore_pages(
                self._cache.pages_flat(), jnp.asarray(idx), stacked))
        end = fresh[-1][0] + 1
        blocks = tokens[:end * self.page_size]
        row = [0] * end
        for j, p in fresh:
            row[j] = p
        pc.register(blocks, row)
        adopted = 0
        for j, p in fresh:
            # uniform release: ref 1 -> 0; a page the register adopted
            # stays resident (cache-owned, LRU-evictable), a page an
            # existing entry beat stays off the index and returns to the
            # free list — leak-free either way
            registered = pc.contains_page(p)
            self._cache.release_page(p)
            if not registered:
                continue
            adopted += 1
            if self._integrity is not None and dev_sums[j] is not None:
                # the shipped bytes hash-matched their capture digest,
                # so the source replica's device-side sum describes the
                # restored page too (same contract as tier promotion)
                self._integrity.adopt_page_sum(p, float(dev_sums[j]))
        if _TRACER.enabled:
            _TRACER.instant("cluster.kv_adopt", "cache",
                            adopted=int(adopted),
                            shipped=int(n_blocks), verified=int(good))
        return adopted

    def _contain_kv_corruption(self, bad_pages):
        """Containment ladder, KV arm (ISSUE 14): a checksum-failed page
        invalidates out of the cache with every descendant block (the
        invalidate-on-doubt path — future lookups miss and recompute),
        and any ACTIVE slot whose table references a bad page is
        preempted: its KV may already be poisoned, and the recompute
        requeue re-prefills prompt+generated exactly (the same
        machinery replica migration rides), so the stream's delivered
        tokens stay bit-identical. Corruption costs a miss or a
        re-prefill — never a wrong token."""
        dead = set()
        for pg in bad_pages:
            for p in self._pcache.invalidate_page(int(pg)):
                dead.add(int(p))
                if self._integrity is not None:
                    self._integrity.forget_page(p)
                if int(self._page_ref[p]) == 0:
                    self._free_pages.append(p)
        dead.update(int(p) for p in bad_pages)
        for slot in list(self._active):
            if any(int(p) in dead for p in self.tables[slot] if p):
                self._preempt(slot)

    def _drop_cow_for(self, row):
        """Cancel pending COW copies whose destination lives in ``row`` —
        called when an admission aborts between splice and dispatch (the
        row's pages are being released, so the copy must not run)."""
        if self._cow_pending:
            dead = {int(p) for p in row if p}
            self._cow_pending = [sd for sd in self._cow_pending
                                 if sd[1] not in dead]

    def _preempt(self, slot):
        """Evict a running request under pool pressure: recycle its pages
        and requeue it — re-admission prefills prompt+generated prefix, and
        the live PRNG key travels with the request, so generation resumes
        exactly where it stopped for greedy AND sampled decode. The vLLM
        recompute-preemption policy."""
        req = self._active.pop(slot)
        req._key = self._keys[slot].copy()
        if self._m is not None:
            self._m.preemptions.inc()
            self._m.page_evictions.inc(
                int(np.count_nonzero(self.tables[slot])))
        self._free_slot(slot)
        req.slot = None
        self._requeue(req)

    def _requeue(self, req):
        """Recompute-policy re-queue with a hard retry bound: a request
        that keeps getting evicted (allocator livelock, repeated step
        faults) fails attributably (``retries_exhausted``) instead of
        spinning forever. Front insertion doubles as priority aging — a
        retried request outranks fresh arrivals at the next admission,
        so retries can't starve it either."""
        req.retries += 1
        if self._m is not None:
            self._m.retries.inc()
        if req.retries > self.max_retries:
            self._fail_request(req, RetriesExhausted(
                f"request {req.rid} re-queued more than max_retries="
                f"{self.max_retries} times", rid=req.rid))
            return
        self._queue.insert(0, req)

    def _free_slot(self, slot):
        if slot in self._free_slots:
            # idempotent release (ISSUE 6 satellite): a double free would
            # hand the same slot to two requests and recycle its pages
            # twice — the second call must be a no-op
            return
        # release every allocated table entry — chain headroom means the
        # slot can hold pages beyond pages_needed(length) (0 is the trash
        # page, never allocated). A slot release DECREMENTS: spliced
        # shared pages survive for their other referents, and pages the
        # prefix cache indexes stay resident at refcount 0
        for p in self.tables[slot]:
            if p:
                self._release_page(int(p))
        self.tables[slot, :] = 0
        self.lengths[slot] = 0
        self._chunk_left.pop(slot, None)  # mid-prefill state dies with the slot
        self._free_slots.append(slot)
        if self._spec is not None:
            # a draft-model drafter mirrors engine slots in its own page
            # pool; recycle its side too (no-op for the ngram drafter)
            self._spec.drafter.release(slot)

    def _reset_pool(self):
        """(Re)create the device page buffers and allocator free lists —
        delegated to the cache-coordinator, which rebuilds a sharded
        pool PER-SHARD (donated-dead buffers after a failed dispatch
        must come back with the same mesh placement, ISSUE 11
        satellite). Content is entirely recomputable: every requeued
        request re-prefills its prompt+generated prefix on re-admission,
        so a fresh zeroed pool loses nothing."""
        self._cache.reset()
        # mid-prefill progress refers to pages that just died; requeued
        # requests re-chunk from scratch (recompute policy)
        if getattr(self, "_chunk_left", None):
            self._chunk_left.clear()
        if getattr(self, "_spec", None) is not None:
            self._spec.drafter.reset()

    def _reserve_step_pages(self, k, target_len):
        """Allocate this step's pages for every active slot — shrinking
        the chain depth, then preempting (retry-bounded), then failing
        the lone unservable request — NEVER raising. ``target_len(slot,
        req, k)`` gives the desired cache length per slot at depth ``k``.
        Returns the depth actually reserved, or 0 once nothing is active
        (every caller re-checks ``self._active``)."""
        while self._active:
            short = failed = False
            for slot in sorted(self._active,
                               key=lambda s: -int(self.lengths[s])):
                req = self._active[slot]
                try:
                    if not self._ensure_pages(slot, target_len(slot, req, k)):
                        short = True
                        break
                except RequestError as e:
                    # per-sequence table overflow and kin: one request's
                    # fault, one request's failure
                    self._fail_request(req, e)
                    failed = True
                    break
            if not short and not failed:
                return k
            # roll back EVERY slot's chain headroom before retrying:
            # pages an earlier (longer) slot grabbed for the failed
            # attempt would otherwise starve the retry and force a
            # preemption that a smaller uniform depth avoids
            for slot in self._active:
                self._trim_pages(slot, int(self.lengths[slot]))
            if failed:
                continue  # the failed request's pages just freed
            if k > 1:
                k = max(1, k // 2)
                continue
            # k == 1 and still short: preempt under the recompute policy.
            # Victim = longest sequence (most pages back), ties broken
            # toward the FEWEST retries so a much-retried request isn't
            # repeatedly chosen (anti-livelock, with max_retries as the
            # hard bound behind it).
            victims = sorted(self._active,
                             key=lambda s: (-int(self.lengths[s]),
                                            self._active[s].retries))
            if len(victims) <= 1:
                # alone and still unservable: pool genuinely cannot hold
                # it (or injection says so) — fail the request, never the
                # engine (pre-ISSUE-6 this was a RuntimeError)
                self._fail_request(self._active[victims[0]], PoolExhausted(
                    "KV page pool exhausted with nothing left to preempt",
                    rid=self._active[victims[0]].rid))
                continue
            self._preempt(victims[0])
        return 0

    # ----------------------------------------------------------- jit bodies
    # Pages travel as a flat list so jit sees ordinary pytrees and donation
    # reuses the (large) page buffers in place. These helpers are PURE with
    # respect to the engine (never mutate self inside a trace).
    def _states_from(self, pages_flat, tables, lengths, prefill_valid=None,
                     verify=False):
        L = self.cfg.num_layers
        kp, vp = pages_flat[:L], pages_flat[L:2 * L]
        sc = pages_flat[2 * L:3 * L] if self.quantized else [None] * L
        return [
            PagedCacheState(kp[i], vp[i], sc[i], tables, lengths,
                            self.page_size, prefill_valid=prefill_valid,
                            verify=verify)
            for i in range(L)
        ]

    @staticmethod
    def _pages_of(states):
        out = [st.k_pages for st in states] + [st.v_pages for st in states]
        if states[0].quantized:
            out += [st.scale_pages for st in states]
        return out

    def _set_pages(self, pages_flat):
        """Host-side writeback after a jitted call returns."""
        self._cache.set_pages(pages_flat)

    def _pages_flat(self):
        return self._cache.pages_flat()

    def _select_token(self, logits, greedy_tok, temps, keys):
        """Shared prefill/decode token selection: argmax where temp == 0,
        top-k temperature sampling otherwise. ``logits`` [B, V] f32,
        ``keys`` [B, 2] uint32. Returns (tok [B] i32, new_keys)."""
        if self.top_k is not None:
            kth = jax.lax.top_k(logits, self.top_k)[0][:, -1]
            logits = jnp.where(logits >= kth[:, None], logits, -jnp.inf)
        splits = jax.vmap(jax.random.split)(keys)  # [B, 2, 2]
        new_keys, step_keys = splits[:, 0], splits[:, 1]
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
        sampled = jax.vmap(jax.random.categorical)(step_keys, scaled)
        tok = jnp.where(temps > 0.0, sampled.astype(jnp.int32),
                        greedy_tok).astype(jnp.int32)
        # only burn key state for slots that actually sample, so greedy
        # requests stay key-independent and mixed batches stay deterministic
        new_keys = jnp.where((temps > 0.0)[:, None], new_keys, keys)
        return tok, new_keys

    def _make_prefill_raw(self, sampling, suffix=False):
        """Raw (unjitted) bucketed-prefill program — one per (sampling?,
        suffix?); the model-runner wraps it (jit, plus shard_map at
        tp>1) and caches per pow2 bucket.

        ``suffix=True`` is the prefix-cache partial-prefill program
        (ISSUE 8): ``lengths_rows`` carries each row's cached token count
        and ``verify=True`` routes attention through the multi-query
        cache-aware path (``paged_state_verify`` honoring per-row
        ``prefill_valid`` widths), so hit rows compute only their uncached
        suffix while miss rows (base 0) reduce to a from-scratch prefill.
        All-miss waves keep this ``suffix=False`` program — bitwise the
        cache-off path, so zero-overlap traffic never pays for the
        cache."""
        model, engine = self.model, self
        moe_n = self._moe_stats_n

        def prefill(params, pages_flat, ids, valid, tables_rows,
                    lengths_rows, temps, keys):
            from ..jit import swapped_tensors

            with swapped_tensors(engine._swap, params), pause_tape():
                states = engine._states_from(pages_flat, tables_rows,
                                             lengths_rows,
                                             prefill_valid=valid,
                                             verify=suffix)
                with _moe_tap(moe_n) as tap:
                    logits, new_states = model.forward(Tensor._wrap(ids),
                                                       caches=states)
                lg = logits._data if isinstance(logits, Tensor) else logits
                last = jnp.take_along_axis(
                    lg, (valid - 1)[:, None, None], axis=1)[:, 0]
                last = last.astype(jnp.float32)
                # NaN/inf logit guard (ISSUE 6): a non-finite row means
                # argmax/sampling is garbage — flag it so the host fails
                # THAT request instead of streaming junk
                bad = ~jnp.all(jnp.isfinite(last), axis=-1)
                greedy = jnp.argmax(last, axis=-1).astype(jnp.int32)
                if sampling:
                    tok, new_keys = engine._select_token(last, greedy,
                                                         temps, keys)
                else:
                    tok, new_keys = greedy, keys
                out = tok, new_keys, bad, engine._pages_of(new_states)
                if moe_n:
                    out += (jnp.sum(jnp.stack(tap), axis=0),)
                return out

        return prefill

    def _get_prefill(self, bucket, sampling, suffix=False):
        """One compiled prefill per (pow2 row count, pow2 prompt bucket,
        sampling?, suffix?): a whole admission wave in one dispatch.
        Greedy-only waves compile without the sampling machinery."""
        return self.runner.get_prefill(bucket, sampling, suffix)

    def _make_decode_raw(self, k, sampling):
        """Raw (unjitted) chained-decode program: a single ``lax.scan``
        of ``k * chunk_size`` steps — the model-runner wraps it (jit +
        shard_map at tp>1; the scan carries the page shards LOCALLY, so
        no reshard crosses a step boundary — the tpushard TPC502
        property the sharded chain is gated on)."""
        model, engine = self.model, self
        steps = k * self.chunk_size
        moe_n = self._moe_stats_n

        def decode_chain(params, pages_flat, tables, lengths, last_tok,
                         temps, keys):
            from ..jit import swapped_tensors

            with swapped_tensors(engine._swap, params), pause_tape():
                def body(carry, _):
                    pages_flat, lengths, last, keys, bad, mstat = carry
                    states = engine._states_from(pages_flat, tables, lengths)
                    # the tap must arm INSIDE the scan body — its traced
                    # stats belong to this iteration; they fold into the
                    # carry accumulator, never escape the body
                    with _moe_tap(moe_n) as tap:
                        logits, new_states = model.forward(
                            Tensor._wrap(last[:, None]), caches=states)
                    if moe_n:
                        mstat = mstat + jnp.sum(jnp.stack(tap), axis=0)
                    lg = (logits._data if isinstance(logits, Tensor)
                          else logits)
                    lg = lg[:, -1].astype(jnp.float32)
                    # NaN/inf logit guard (ISSUE 6): OR-accumulated per
                    # row across the chain; the host fails flagged rows
                    bad = bad | ~jnp.all(jnp.isfinite(lg), axis=-1)
                    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    if sampling:
                        nxt, keys = engine._select_token(lg, greedy, temps,
                                                         keys)
                    else:
                        nxt = greedy
                    # idle slots keep emitting garbage; host discards
                    return ((engine._pages_of(new_states),
                             new_states[0].lengths, nxt, keys, bad,
                             mstat), nxt)

                (pages_flat, lengths, _, keys, bad, mstat), toks = \
                    jax.lax.scan(
                        body, (pages_flat, lengths, last_tok, keys,
                               jnp.zeros(last_tok.shape, bool),
                               jnp.zeros((moe_n,), jnp.float32)), None,
                        length=steps)
            out = (jnp.swapaxes(toks, 0, 1), pages_flat, lengths, keys,
                   bad)
            if moe_n:
                out += (mstat,)
            return out

        return decode_chain

    def _get_decode(self, nb, k, sampling):
        """One compiled decode program per (pow2 active-slot bucket ``nb``,
        pow2 chain depth ``k``, sampling?): a whole chain costs ONE
        dispatch + ONE fetch (on the tunneled chip a dispatch is
        ~50–100 ms — chaining k separate chunk dispatches still paid it
        k times). Greedy-only batches compile without the per-step
        vocab-wide sampling draw."""
        return self.runner.get_decode(nb, k, sampling)

    def _get_mixed(self, nb, sampling):
        """ONE compiled mixed chunk+decode step per sampling flag
        (ISSUE 9): rows pad to the fixed max_slots bucket and the token
        axis is the static ``prefill_chunk``, so chunked serving's whole
        compile surface is this program plus the decode chains — no
        prompt-length prefill buckets, which is what lets a cold server's
        first wave approach steady-state throughput."""
        return self.runner.get_mixed(nb, sampling)

    # ------------------------------------------------------------ scheduling
    @staticmethod
    def _prefix(req):
        """Tokens that must be in the cache before decode continues: the
        prompt plus anything already generated (non-empty after a
        preemption — re-prefilling the full prefix resumes generation)."""
        if req.tokens:
            return np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])
        return req.prompt

    def _admit_dispatch(self):
        """Dispatch one bucketed prefill for ALL admissible queued
        requests WITHOUT blocking (rows pad to pow2, prompts to a shared
        pow2 bucket). Returns ``(admits, tok_dev, keys_dev)`` — device
        handles the caller threads into the same step's decode chain and
        harvests with the chain's fetch, so admission costs no host sync
        of its own (VERDICT r4 #2)."""
        # land any finished spill/promote completions first (ISSUE 15):
        # a promotion that arrived since the last step makes THIS wave's
        # lookups splice instead of recompute
        self._cache.drain_tier()
        admits = []  # (req, slot, prefix, base)
        while (self._queue and self._free_slots
               and len(self._active) + len(admits) < self._slot_cap):
            # _slot_cap == max_slots when healthy; the watchdog halves it
            # in SMALL_BATCH degraded mode (less page pressure, smaller
            # blast radius) and restores it on recovery
            req = self._queue[0]
            prefix = self._prefix(req)
            need = self._pages_needed(prefix.size + self.chunk_size)
            if self._pcache is not None:
                # a cached prefix shrinks the allocation this admission
                # actually needs (peek only — no LRU touch, no hit/miss
                # accounting until the splice commits)
                _, peeked = self._pcache.lookup(prefix, touch=False)
                reuse = peeked // self.page_size
                if peeked and peeked == int(prefix.size):
                    reuse -= 1  # the COW copy still needs a fresh page
                need -= reuse
            if need > self._available_pages():
                break  # pool pressure: let running requests drain first
            slot = self._free_slots.pop()
            self._queue.pop(0)
            base = self._splice_prefix(self.tables[slot], prefix)
            # attribute the splice's KV-tier promote wait to THIS
            # request's TTFT decomposition (first admission only —
            # re-admission after preemption is preemption cost, just
            # like queue-wait in _note_admitted)
            if not req._admitted:
                req._t_promote_wait += self._last_promote_wait_s
            try:
                got = self._ensure_pages(slot, prefix.size)
            except RequestError as e:
                self._drop_cow_for(self.tables[slot])
                self._free_slot(slot)
                self._fail_request(req, e)
                continue
            if not got:
                self._drop_cow_for(self.tables[slot])
                self._free_slot(slot)
                self._queue.insert(0, req)
                break
            admits.append((req, slot, prefix, base))
        if not admits:
            return [], None, None, None
        # register the wave for step-fault recovery BEFORE the prefill
        # dispatch: these requests were popped from _queue but are not in
        # _active until the commit below, so a trace/dispatch error here
        # used to lose them from the engine entirely — the request never
        # reached a terminal state and its stream (and any router ticket
        # waiting on it) hung forever instead of failing attributably
        self._pending_inflight = admits
        tok, new_keys, bad = self._prefill_wave(
            [(req, prefix, self.tables[slot], base)
             for req, slot, prefix, base in admits])
        # commit host bookkeeping now; token values arrive at harvest
        for req, slot, prefix, _base in admits:
            self.lengths[slot] = prefix.size
            req.slot = slot
            self._active[slot] = req
            self._temps[slot] = req.temperature
            # commit the PRE-prefill key now (the post-draw key arrives at
            # harvest): if a step fault forces recovery before the
            # harvest, re-prefilling from this key replays the same draw,
            # so even a sampled stream resumes exactly (ISSUE 6)
            if req._key is not None:
                self._keys[slot] = req._key
            self._note_admitted(req)
        self._pending_inflight = []
        return admits, tok, new_keys, bad

    def _note_admitted(self, req):
        """Queue-wait telemetry: first slot admission only (re-admission
        after preemption is preemption cost, already counted there)."""
        if req._admitted:
            return
        req._admitted = True
        req._t_admit = time.perf_counter()
        if self._m is not None:
            self._m.queue_wait_for(req.tenant).observe(
                req._t_admit - req._t_arrival)
        if _TRACER.enabled:
            _TRACER.instant("engine.admit", "engine",
                            parent=req.trace, rid=req.rid,
                            slot=req.slot,
                            promote_wait_s=req._t_promote_wait)

    def _prefill_wave(self, rows):
        """Dispatch ONE bucketed prefill for ``rows`` of (req, prefix,
        table_row, base) — shared by admission and pre-admission. Returns
        the (tok, keys) device handles; never blocks.

        ``base`` is the row's cached-prefix token count (prefix cache,
        ISSUE 8): any hit in the wave routes the WHOLE wave through the
        suffix program (cache-aware multi-query attention; miss rows with
        base 0 behave exactly like a prefill), the seq bucket shrinks to
        the longest uncached SUFFIX, and pending copy-on-write page
        duplications flush in one device dispatch first. An all-miss wave
        keeps the classic prefill program — bitwise the cache-off path.

        The pow2 seq bucket caps at max_position so prefill position ids
        (arange over the padded width) never index past the embedding
        table (ADVICE r3: don't rely on XLA's OOB-gather clamping). Rows
        pad to the FIXED max_slots bucket, not the wave size: a variable
        row axis multiplies the compiled-program space and lets
        scheduling nondeterminism hit novel shapes long after warmup (a
        39 s Mosaic compile observed mid-serve); padding rows write to
        the trash page, costing ~one chunk of compute at these slot
        counts. Deployments with very large max_slots would revisit."""
        if self._m is not None:
            self._m.prefill_batch.observe(len(rows))
        if _TRACER.enabled:
            _TRACER.instant(
                "engine.prefill_wave", "engine", wave=len(rows),
                rids=[req.rid for req, *_ in rows])
        self._flush_cow()
        suffix_mode = any(base for *_, base in rows)
        if suffix_mode and self._m is not None:
            # the suffix program rides the fused verify/suffix slab
            # attention path (ISSUE 9) — count the dispatch
            self._m.slab_dispatch.labels(path="suffix_prefill").inc()
        seq_bucket = min(_pow2ceil(max(p.size - b for _, p, _, b in rows)),
                         self.cfg.max_position)
        nb = _pow2ceil(self.max_slots)
        ids = np.zeros((nb, seq_bucket), np.int32)
        valid = np.ones((nb,), np.int32)  # pad rows: 1 token → trash page
        bases = np.zeros((nb,), np.int32)
        tables = np.zeros((nb, self.max_pages_per_seq), np.int32)
        temps = np.zeros((nb,), np.float32)
        keys = np.zeros((nb, 2), np.uint32)
        for i, (req, prefix, table_row, base) in enumerate(rows):
            suf = prefix[base:]
            ids[i, :suf.size] = suf
            valid[i] = suf.size
            bases[i] = base
            tables[i] = table_row
            temps[i] = req.temperature
            if self._m is not None:
                self._m.pc_computed_tokens.inc(int(suf.size))
            if req._key is None:
                seed = int(req.seed if req.seed is not None else req.rid)
                # threefry2x32 key layout, built host-side — going through
                # jax.random.PRNGKey here costs a device round trip (~100 ms
                # on the tunnel) PER ADMISSION
                req._key = np.array(
                    [(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
                    np.uint32)
            keys[i] = req._key
        prefill = self._get_prefill((nb, seq_bucket),
                                    bool(np.any(temps > 0.0)), suffix_mode)
        tok, new_keys, bad, pages_flat, *ex = prefill(
            self._params, self._pages_flat(), jnp.asarray(ids),
            jnp.asarray(valid), jnp.asarray(tables),
            jnp.asarray(bases), jnp.asarray(temps),
            jnp.asarray(keys))
        self._set_pages(pages_flat)
        self._note_moe_stats(ex)
        return tok, new_keys, bad

    # ------------------------------------------ MoE router stats (ISSUE 17)
    def _note_moe_stats(self, ex):
        """Stash the trailing router-stats device handle an MoE
        program's dispatch returned (``ex`` is the splat-captured tail —
        empty on dense engines). Non-blocking; drained at the step
        boundary / :meth:`moe_stats`. The soft cap bounds growth when a
        caller dispatches outside ``step()`` (e.g. blocking admission
        in a tight loop) — by then the producing program's sibling
        outputs were fetched, so the drain's ``device_get`` is cheap."""
        if ex:
            self._moe_pending.append(ex[0])
            if len(self._moe_pending) > 64:
                self._drain_moe_stats()

    def _drain_moe_stats(self):
        """Fold pending router-stats vectors into the host aggregate and
        record the MoE metrics (HOST code between dispatches — TPL601)."""
        if not self._moe_pending:
            return
        pend, self._moe_pending = self._moe_pending, []
        try:
            vals = jax.device_get(tuple(pend))
        except Exception:  # tpulint: disable=TPL701 -- observability drain: the producing step's OWN harvest already routed this failure through _recover_step_fault; the stats sibling dying with it is the recovery contract, and a metrics drain must never take down the scheduler
            return
        agg = np.zeros_like(self._moe_tot)
        for v in vals:
            agg += np.asarray(v, np.float64)
        self._moe_tot += agg
        if _TRACER.enabled:
            e = self._moe_stats_n - 3
            _TRACER.instant("engine.moe_dispatch", "moe",
                            dispatches=len(pend),
                            kept=float(np.sum(agg[:e])),
                            dropped=float(agg[e]))
        if self._m is not None:
            e = self._moe_stats_n - 3
            if agg[e]:
                self._m.moe_dropped.inc(float(agg[e]))
            for i in range(e):
                if agg[i]:
                    self._m.moe_expert_at(i).inc(float(agg[i]))
            routed = float(agg[e + 2])
            if routed > 0:
                self._m.moe_router_entropy.set(float(agg[e + 1]) / routed)

    def moe_stats(self) -> Dict[str, object]:
        """Cumulative MoE routing stats since engine construction
        (bench.py's metrics tail and serve_llama_paged's stats line read
        this). ``{}`` on dense engines. ``drop_frac`` is dropped pairs /
        total routed pairs (kept + dropped); ``load_imbalance`` is
        max/mean over the per-expert kept counts (1.0 = perfectly
        balanced); ``router_entropy`` is the per-token mean in nats."""
        if not self._moe_stats_n:
            return {}
        self._drain_moe_stats()
        e = self._moe_stats_n - 3
        t = self._moe_tot
        load = t[:e]
        kept = float(load.sum())
        dropped = float(t[e])
        pairs = kept + dropped
        routed = float(t[e + 2])
        mean = kept / e if e else 0.0
        return {
            "tokens_routed": routed,
            "pairs_kept": kept,
            "pairs_dropped": dropped,
            "drop_frac": dropped / pairs if pairs else 0.0,
            "expert_load": [float(x) for x in load],
            "load_imbalance": float(load.max()) / mean if mean > 0 else 0.0,
            "router_entropy": float(t[e + 1]) / routed if routed else 0.0,
        }

    def _flush_cow(self):
        """Flush pending copy-on-write page duplications in one device
        dispatch — owed BEFORE any program writes into a spliced table."""
        if self._cow_pending:
            src = np.asarray([s for s, _ in self._cow_pending], np.int32)
            dst = np.asarray([d for _, d in self._cow_pending], np.int32)
            self._set_pages(_copy_pages(self._pages_flat(),
                                        jnp.asarray(src), jnp.asarray(dst)))
            self._cow_pending = []

    def _admit(self):
        """Blocking admission (compat surface for tests/tools that admit
        outside a step): dispatch + immediate harvest."""
        admits, tok_dev, keys_dev, bad_dev = self._admit_dispatch()
        if admits:
            self._harvest_admits(admits, *jax.device_get(
                (tok_dev, keys_dev, bad_dev)))
        return [r for r, *_ in admits]

    def _harvest_admits(self, admits, first, new_keys, bad):
        first = np.asarray(first)
        new_keys = np.asarray(new_keys)
        bad = np.asarray(bad)
        for i, (req, slot, prefix, _base) in enumerate(admits):
            try:
                if self._fi is not None:
                    if self._fi.fire("step-exception", rid=req.rid):
                        raise InjectedFault(
                            f"injected step fault (rid {req.rid})")
                    if self._fi.fire("nan-logits", rid=req.rid):
                        raise NumericsError(
                            "injected non-finite logits", rid=req.rid)
                if bad[i]:
                    raise NumericsError(
                        "non-finite logits at prefill", rid=req.rid)
                if req.slot != slot:
                    # preempted between dispatch and harvest: keep the
                    # token it generated (the re-prefill prefix includes
                    # it) and the post-prefill key so a sampled stream
                    # resumes exactly; no slot bookkeeping — the slot was
                    # freed
                    self._harvest(req, [int(first[i])])
                    req._key = new_keys[i].copy()
                    if req.done and req in self._queue:
                        self._queue.remove(req)  # budget met at prefill
                    continue
                self._keys[slot] = new_keys[i]
                # the prefix KV just computed is now valid on device:
                # publish its full pages for future admissions (before
                # harvest, so even a finished-at-prefill or callback-
                # failed request leaves its prompt cached)
                self._register_prefix(prefix, self.tables[slot])
                self._harvest(req, [int(first[i])])
                self._last_tok[slot] = int(first[i])
                if req.done:  # single remaining token: finished at prefill
                    del self._active[slot]
                    self._free_slot(slot)
                    req.slot = None
            except RequestError as e:
                self._fail_request(req, e)
            except Exception as e:
                # anything else while processing ONE request fails that
                # request, not the batch (per-request isolation)
                self._fail_request(req, self._wrap_step_fault(e, req))

    def _harvest(self, req, toks) -> int:
        """Append generated tokens to a request, honoring eos/max. Returns
        the number of tokens actually CONSUMED — a multi-token append (a
        decode chain's overshoot, or a spec verify block with an eos or
        budget edge mid-block) truncates, and the caller needs the real
        count to roll the slot's KV length/pages back to match (ISSUE 5
        satellite: eos mid-block must not leave post-eos rows live)."""
        was_done = req.done
        fresh = []
        for t in toks:
            if req.done or len(req.tokens) >= req.max_new_tokens:
                req.done = True
                break
            req.tokens.append(int(t))
            fresh.append(int(t))
            if self.eos_id is not None and t == self.eos_id:
                req.done = True
            elif len(req.tokens) >= req.max_new_tokens:
                req.done = True
        if self._m is not None:
            if fresh:
                self._m.on_harvest(req, len(fresh))
            if req.done and not was_done:
                self._m.completed.inc()
        if _TRACER.enabled and fresh:
            # the flight recorder's "victim's last decode steps": one
            # instant per harvest, carrying the delivered tokens
            _TRACER.instant("engine.harvest", "engine",
                            parent=req.trace, rid=req.rid,
                            fresh=len(fresh), total=len(req.tokens),
                            done=req.done)
        if fresh and req.on_token is not None:
            try:
                req.on_token(fresh)
            except Exception as e:
                # the streaming callback belongs to the CALLER; its crash
                # fails this request (reason "callback" — tokens up to
                # here were delivered), never the batch. Every _harvest
                # call site sits inside a per-request isolation block.
                err = CallbackError(
                    f"on_token raised {type(e).__name__}: {e}", rid=req.rid)
                err.__cause__ = e
                raise err
        return len(fresh)

    # pre-measurement PRIOR for the cost of a chain boundary (dispatch +
    # blocking fetch) in units of one chunk's compute time. Only seeds
    # ``_dispatch_ratio`` until real step timings replace it — on the
    # tunneled single-chip setup the measured value lands near 8 (~80 ms
    # RTT vs ~20 ms chunk compute); on a direct-attached chip it measures
    # near 0 and the depth maximizer stops over-chaining (VERDICT r4 #2:
    # no transport-tuned magic constant).
    DISPATCH_COST_CHUNKS_PRIOR = 8.0

    def _observe_chain_time(self, nb, k, wall):
        """EMA the wall time of a pure-decode step at (bucket ``nb``,
        depth ``k``); with two distinct depths observed AT THE SAME
        BUCKET (chunk compute differs across buckets), T(k) = rtt +
        k*chunk_time yields the measured rtt/chunk ratio."""
        self._chain_obs += 1
        bucket = self._chain_time_ema.setdefault(nb, {})
        ema = bucket.get(k)
        bucket[k] = wall if ema is None else 0.7 * ema + 0.3 * wall
        ks = sorted(bucket)
        if len(ks) >= 2:
            k1, k2 = ks[0], ks[-1]
            t1, t2 = bucket[k1], bucket[k2]
            chunk_t = (t2 - t1) / (k2 - k1)
            # require a significant positive slope: timing jitter between
            # two near-equal EMAs would otherwise fit an absurd ratio
            if chunk_t > 0.02 * t1 / k1:
                ratio = min(max(0.0, (t1 - k1 * chunk_t) / chunk_t), 64.0)
                self._dispatch_ratio = (
                    ratio if self._dispatch_ratio is None
                    else 0.7 * self._dispatch_ratio + 0.3 * ratio)

    def _boundary_cost_chunks(self):
        return (self._dispatch_ratio if self._dispatch_ratio is not None
                else self.DISPATCH_COST_CHUNKS_PRIOR)

    def _chain_depth(self):
        """Chunks to chain before the next host fetch. Ending the chain
        the moment the first slot finishes (min over remaining) lets one
        straggler force tiny chains — and every chain boundary pays a full
        host round trip. Instead pick the pow2 depth (pow2 keeps the
        (bucket, depth) compile cache ≤ log2·log2 programs) that maximizes
        USEFUL tokens per unit time: stragglers may overshoot (their
        overshoot writes land in pages the harvest frees anyway and the
        tokens are discarded), which costs bounded garbage compute but
        saves a round trip per straggler."""
        rem = [req.max_new_tokens - len(req.tokens)
               for req in self._active.values()]
        kmax = self.max_chain
        if self._queue and self.eos_id is not None:
            # requests are WAITING and completions are UNPREDICTABLE
            # (eos): end the chain when the first slot can finish so it
            # turns over to the queue — deep chains would hold a finished
            # slot hostage for up to max_chain*chunk_size steps and wreck
            # queued-request time-to-first-token. Without an eos,
            # pre-admission prefills the replacement in the chain's
            # shadow, so turnover no longer needs early boundaries and
            # the useful-tokens-per-cost maximizer below decides alone
            # (waiting requests still pay their TTFT until the boundary —
            # the throughput/TTFT trade the reference's serving loop
            # makes the same way under continuous batching).
            kmax = min(kmax, max(1, -(-min(rem) // self.chunk_size)))
        cost = self._boundary_cost_chunks()
        best_k, best_u = 1, -1.0
        k = 1
        while k <= kmax:
            useful = sum(min(r, k * self.chunk_size) for r in rem)
            u = useful / (cost + k)
            if u > best_u:
                best_k, best_u = k, u
            k *= 2
        if (self._dispatch_ratio is None and self._probe_budget > 0
                and self._chain_obs >= 3
                and all(len(b) == 1
                        for b in self._chain_time_ema.values())):
            # steady single-depth workload: T(k) at ONE depth cannot
            # separate rtt from chunk time — probe a neighboring depth
            # (a slightly sub-optimal chain buys the calibration that
            # replaces the transport-tuned prior). STRICTLY bounded: a
            # noisy slope that keeps failing the significance guard must
            # not turn every steady-state step into a probe (measured
            # -13% steady decode when it did). Stays within kmax — the
            # straggler clamp protects queued requests' TTFT.
            probe = best_k // 2 if best_k > 1 else 2
            if 1 <= probe <= kmax and probe != best_k:
                self._probe_budget -= 1
                return probe
        return best_k


    def _alloc_len(self, req, k):
        """Page allocation target for a chained slot: the chain writes
        ``k * chunk_size`` tokens unconditionally, but tokens past the
        request's own budget are garbage — cap the allocation there and
        let the page-write clip route overshoot to the trash page."""
        limit = req.prompt.size + req.max_new_tokens + 1
        return min(int(self.lengths[req.slot]) + k * self.chunk_size, limit)

    def _alloc_row(self, length, prefix=None):
        """Allocate a STANDALONE page-table row (not bound to a slot) for
        a pre-admitted request's prefill, splicing any cached prefix of
        ``prefix`` first. Returns ``(row, base)`` or ``(None, 0)``."""
        need = self._pages_needed(length)
        if need > self.max_pages_per_seq:
            return None, 0
        row = np.zeros((self.max_pages_per_seq,), np.int32)
        base = (self._splice_prefix(row, prefix)
                if prefix is not None else 0)
        for i in range(int(np.count_nonzero(row)), need):
            page = self._alloc_page()
            if page is None:
                self._free_row(row)
                return None, 0
            row[i] = page
        return row, base

    def _free_row(self, row):
        self._drop_cow_for(row)
        for p in row:
            if p:
                self._release_page(int(p))

    def _preadmit_dispatch(self, k, exclude=()):
        """PRE-ADMISSION (VERDICT r4 #2, the last serve-vs-steady gap):
        while the just-dispatched chain runs, prefill the queue heads
        that will take over the slots the chain is PREDICTED to free.
        Without an eos the prediction is exact (budgets are host-known),
        so at harvest the new requests activate into the freed slots and
        start decoding at the very next boundary — the turnover's prefill
        round trip vanishes into the chain's shadow. Prefills land in
        freshly allocated pages (never the completing slots' — no overlap
        with in-flight writes); a prediction miss (only possible with
        eos set, which gates this off entirely) would requeue + recompute.
        Returns (pending, tok_dev, keys_dev)."""
        if self.eos_id is not None or not self._queue \
                or self.prefill_chunk is not None:
            # chunked mode: admission belongs to the mixed step (a
            # pre-admission wave would compile the very prompt-length
            # prefill buckets chunking exists to avoid)
            return [], None, None, None
        horizon = k * self.chunk_size
        n_pred = sum(
            1 for req in self._active.values()
            if req.max_new_tokens - len(req.tokens) <= horizon)
        if not n_pred:
            return [], None, None, None
        pending = []  # (req, row, prefix, base)
        while self._queue and len(pending) < n_pred:
            req = self._queue[0]
            if req in exclude:
                # admitted-then-preempted THIS step: its admit prefill is
                # still in flight and its first token/key only arrive at
                # the harvest fence — re-prefilling now would double-count
                # that token (code-review r5). Stop (not skip): taking a
                # later request over the queue head would break FIFO.
                break
            prefix = self._prefix(req)
            row, base = self._alloc_row(prefix.size + self.chunk_size,
                                        prefix)
            if row is None:
                break  # pool pressure: normal admission will retry later
            self._queue.pop(0)
            pending.append((req, row, prefix, base))
        if not pending:
            return [], None, None, None
        # same step-fault-recovery registration as the admission wave:
        # pre-admitted requests are in neither _queue nor _active until
        # _activate_pending commits, and the caller's own registration
        # happens only AFTER this dispatch returns — a trace/dispatch
        # fault inside the wave used to black-hole the whole batch
        self._pending_inflight = pending
        tok, new_keys, bad = self._prefill_wave(
            [(req, prefix, row, base) for req, row, prefix, base in pending])
        return pending, tok, new_keys, bad

    def _activate_pending(self, pending, first, new_keys, bad):
        """Post-harvest: move pre-admitted requests into the slots the
        chain freed (their caches are already warm). Each request is its
        own isolation domain: a fault here fails it alone, and its
        standalone page row is returned whichever path it dies on."""
        first = np.asarray(first)
        new_keys = np.asarray(new_keys)
        bad = np.asarray(bad)
        for i, (req, row, prefix, _base) in enumerate(pending):
            try:
                if self._fi is not None:
                    if self._fi.fire("step-exception", rid=req.rid):
                        raise InjectedFault(
                            f"injected step fault (rid {req.rid})")
                    if self._fi.fire("nan-logits", rid=req.rid):
                        raise NumericsError(
                            "injected non-finite logits", rid=req.rid)
                if bad[i]:
                    raise NumericsError(
                        "non-finite logits at pre-admission prefill",
                        rid=req.rid)
                # the prefix KV in this row is valid on device: publish
                # its full pages — even the prediction-miss path below
                # then requeues into a warm cache instead of recomputing
                self._register_prefix(prefix, row)
                if not self._free_slots:
                    # prediction miss (cannot happen with eos gating; kept
                    # as a correctness net): recompute policy — requeue
                    # with the generated token folded into the prefix
                    self._free_row(row)
                    row = None  # ownership returned before harvest
                    self._harvest(req, [int(first[i])])
                    req._key = new_keys[i].copy()
                    if not req.done:
                        self._queue.insert(0, req)
                    continue
                slot = self._free_slots.pop()
                self.tables[slot] = row
                self.lengths[slot] = prefix.size
                req.slot = slot  # row ownership now travels with the slot
                self._active[slot] = req
                self._temps[slot] = req.temperature
                self._keys[slot] = new_keys[i]
                self._note_admitted(req)
                self._harvest(req, [int(first[i])])
                self._last_tok[slot] = int(first[i])
                if req.done:
                    del self._active[slot]
                    self._free_slot(slot)
                    req.slot = None
            except RequestError as e:
                if req.slot is None and row is not None:
                    self._free_row(row)
                self._fail_request(req, e)
            except Exception as e:
                if req.slot is None and row is not None:
                    self._free_row(row)
                self._fail_request(req, self._wrap_step_fault(e, req))

    def _wants_mixed(self) -> bool:
        """Route to the mixed chunk+decode step? Yes while any prompt is
        mid-stream, or when a queued request could take a slot (the
        mixed step owns admission in chunked mode). Pure-decode phases
        fall back to the chained path — deep chains amortize the host
        round trip far better than depth-1 mixed steps."""
        if self.prefill_chunk is None:
            return False
        if self._chunk_left:
            return True
        return bool(self._queue) and bool(self._free_slots) \
            and len(self._active) < self._slot_cap

    def _bind_chunked(self):
        """Chunked-mode admission: bind queued requests to slots WITHOUT
        a prefill dispatch — their first chunk rides the very next mixed
        (or disaggregated prefill-role) step. Shared by ``_mixed_step``
        and ``_disagg_step``."""
        chunk = self.prefill_chunk
        self._cache.drain_tier()  # promoted pages splice this admission
        while (self._queue and self._free_slots
               and len(self._active) < self._slot_cap):
            req = self._queue[0]
            prefix = self._prefix(req)
            # pages this admission needs NOW: the first chunk only —
            # later chunks allocate step by step, so a long prompt's
            # tail never holds pages before the tokens arrive
            need = self._pages_needed(min(prefix.size, chunk))
            if self._pcache is not None:
                _, peeked = self._pcache.lookup(prefix, touch=False)
                reuse = peeked // self.page_size
                if peeked and peeked == int(prefix.size):
                    reuse -= 1  # the COW copy still needs a fresh page
                need = max(0, self._pages_needed(
                    min(prefix.size, peeked + chunk)) - reuse)
            if need > self._available_pages():
                break  # pool pressure: let running requests drain first
            slot = self._free_slots.pop()
            self._queue.pop(0)
            base = self._splice_prefix(self.tables[slot], prefix)
            # attribute the splice's KV-tier promote wait to THIS
            # request's TTFT decomposition (first admission only —
            # re-admission after preemption is preemption cost, just
            # like queue-wait in _note_admitted)
            if not req._admitted:
                req._t_promote_wait += self._last_promote_wait_s
            try:
                got = self._ensure_pages(
                    slot, min(prefix.size, base + chunk))
            except RequestError as e:
                self._drop_cow_for(self.tables[slot])
                self._free_slot(slot)
                self._fail_request(req, e)
                continue
            if not got:
                self._drop_cow_for(self.tables[slot])
                self._free_slot(slot)
                self._queue.insert(0, req)
                break
            self.lengths[slot] = base
            self._chunk_left[slot] = prefix[base:]
            req.slot = slot
            self._active[slot] = req
            self._temps[slot] = req.temperature
            if req._key is None:
                seed = int(req.seed if req.seed is not None else req.rid)
                # threefry2x32 key layout, built host-side (see
                # _prefill_wave: PRNGKey costs a device round trip)
                req._key = np.array(
                    [(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
                    np.uint32)
            self._keys[slot] = req._key
            self._note_admitted(req)

    def _mixed_step(self):
        """Chunked-prefill scheduling iteration (ISSUE 9 tentpole b).
        Admission binds queued requests to slots WITHOUT a prefill
        dispatch — their first chunk rides this very step — then one
        fixed-shape mixed program advances every active slot: decoding
        slots by one token, prefilling slots by up to ``prefill_chunk``
        prompt tokens. Long prompts never stall the decode batch (decode
        tokens land every step while the prompt streams in), pages
        allocate chunk-by-chunk instead of prompt-at-once, and the whole
        wave harvests with one blocking fetch."""
        chunk = self.prefill_chunk
        self._bind_chunked()
        if not self._active:
            if self._queue:
                self._note_stall()
            return
        self._stall_steps = 0

        def target(slot, req, _k):
            left = self._chunk_left.get(slot)
            if left is not None:
                return int(self.lengths[slot]) + min(left.size, chunk)
            return min(int(self.lengths[slot]) + 1,
                       req.prompt.size + req.max_new_tokens + 1)

        # allocate this step's pages — shrink (no-op at depth 1), then
        # preempt, then fail the lone unservable request, never raise; a
        # preempted mid-prefill slot drops its _chunk_left with the slot
        # and re-chunks from scratch on re-admission (recompute policy)
        self._reserve_step_pages(1, target)
        if not self._active:
            return
        slots, widths, tok_d, keys_d, bad_d = self._mixed_dispatch(
            sorted(self._active))
        tok, keys_h, bad_h = (np.asarray(a) for a in jax.device_get(
            (tok_d, keys_d, bad_d)))
        self._mixed_harvest(slots, widths, tok, keys_h, bad_h)

    def _mixed_dispatch(self, slots):
        """Build + dispatch ONE mixed chunk+decode program over exactly
        ``slots`` (rows pad to the fixed max_slots bucket; slots not
        listed — e.g. the decode-role batch of a disaggregated step —
        simply aren't rows). Returns device handles; never blocks."""
        chunk = self.prefill_chunk
        n = len(slots)
        nb = _pow2ceil(self.max_slots)
        ids = np.zeros((nb, chunk), np.int32)
        widths = np.ones((nb,), np.int32)  # pad rows: width 1 → trash page
        emit = np.zeros((nb,), np.int32)
        tables_c = np.zeros((nb, self.max_pages_per_seq), np.int32)
        lengths_c = np.zeros((nb,), np.int32)
        temps_c = np.zeros((nb,), np.float32)
        keys_c = np.zeros((nb, 2), np.uint32)
        tables_c[:n] = self.tables[slots]
        lengths_c[:n] = self.lengths[slots]
        temps_c[:n] = self._temps[slots]
        keys_c[:n] = self._keys[slots]
        n_chunks = chunk_toks = 0
        for i, slot in enumerate(slots):
            left = self._chunk_left.get(slot)
            if left is not None:
                w = min(left.size, chunk)
                ids[i, :w] = left[:w]
                widths[i] = w
                emit[i] = int(w == left.size)
                n_chunks += 1
                chunk_toks += w
            else:
                ids[i, 0] = self._last_tok[slot]
                emit[i] = 1
        if self._m is not None:
            self._m.decode_batch.observe(n)
            if n_chunks:
                self._m.prefill_chunks.inc(n_chunks)
                self._m.pc_computed_tokens.inc(chunk_toks)
            self._m.slab_dispatch.labels(path="chunked_prefill").inc()
        if _TRACER.enabled and n_chunks:
            _TRACER.instant("engine.prefill_chunk", "engine",
                            chunks=n_chunks, tokens=chunk_toks,
                            decode_rows=n - n_chunks)
        self._flush_cow()
        sampling = bool(np.any(temps_c > 0.0))
        mixed = self._get_mixed(nb, sampling)
        tok_d, keys_d, bad_d, pages, *ex = mixed(
            self._params, self._pages_flat(), jnp.asarray(ids),
            jnp.asarray(widths), jnp.asarray(emit),
            jnp.asarray(tables_c), jnp.asarray(lengths_c),
            jnp.asarray(temps_c), jnp.asarray(keys_c))
        self._set_pages(pages)
        self._note_moe_stats(ex)
        return slots, widths, tok_d, keys_d, bad_d

    def _mixed_harvest(self, slots, widths, tok, keys_h, bad_h):
        """Host harvest of a mixed dispatch: advance chunk state, take
        tokens from emitting rows, per-request fault isolation."""
        cap = self.max_pages_per_seq * self.page_size
        for i, slot in enumerate(slots):
            req = self._active.get(slot)
            if req is None or req.slot != slot:
                continue  # failed between dispatch and harvest
            try:
                if self._fi is not None:
                    if self._fi.fire("step-exception", rid=req.rid):
                        raise InjectedFault(
                            f"injected step fault (rid {req.rid})")
                    if self._fi.fire("nan-logits", rid=req.rid):
                        raise NumericsError(
                            "injected non-finite logits", rid=req.rid)
                if bad_h[i]:
                    raise NumericsError(
                        "non-finite logits in mixed chunk step",
                        rid=req.rid)
                self.lengths[slot] = min(
                    int(self.lengths[slot]) + int(widths[i]), cap)
                left = self._chunk_left.get(slot)
                if left is not None and int(widths[i]) < left.size:
                    # mid-prompt chunk: the KV landed; the emitted token
                    # predicts a prompt token we already have — discard
                    self._chunk_left[slot] = left[int(widths[i]):]
                    continue
                if left is not None:
                    # final chunk: prompt fully resident — publish it to
                    # the prefix cache and take the first generated
                    # token, exactly where classic prefill takes it
                    del self._chunk_left[slot]
                    self._register_prefix(self._prefix(req),
                                          self.tables[slot])
                self._keys[slot] = keys_h[i]
                self._harvest(req, [int(tok[i])])
                self._last_tok[slot] = int(tok[i])
                if req.done:
                    del self._active[slot]
                    self._free_slot(slot)
                    req.slot = None
            except RequestError as e:
                self._fail_request(req, e)
            except Exception as e:
                self._fail_request(req, self._wrap_step_fault(e, req))

    # ------------------------------- prefill/decode disaggregation (ISSUE 11)
    def _chain_dispatch(self, slots, k):
        """Dispatch a decode chain over exactly ``slots`` (compacted to
        their own pow2 bucket) — the decode-role half of a disaggregated
        step. No admission splicing, no pre-admission: those belong to
        the chunked admission path. Returns the chain tuple; never
        blocks."""
        slot_reqs = [self._active[s] for s in slots]
        n = len(slots)
        nb = _pow2ceil(n)
        if self._m is not None:
            self._m.chain_depth_at(k).inc()
            self._m.decode_batch.observe(n)
        tables_c = np.zeros((nb, self.max_pages_per_seq), np.int32)
        lengths_c = np.zeros((nb,), np.int32)
        last_c = np.zeros((nb,), np.int32)
        temps_c = np.zeros((nb,), np.float32)
        keys_c = np.zeros((nb, 2), np.uint32)
        tables_c[:n] = self.tables[slots]
        lengths_c[:n] = self.lengths[slots]
        last_c[:n] = self._last_tok[slots]
        temps_c[:n] = self._temps[slots]
        keys_c[:n] = self._keys[slots]
        sampling = bool(np.any(temps_c > 0.0))
        decode = self._get_decode(nb, k, sampling)
        toks_d, pages, lengths_d, keys_d, bad_d, *ex = decode(
            self._params, self._pages_flat(), jnp.asarray(tables_c),
            jnp.asarray(lengths_c), jnp.asarray(last_c),
            jnp.asarray(temps_c), jnp.asarray(keys_c))
        self._set_pages(pages)
        self._note_moe_stats(ex)
        return (slots, slot_reqs, toks_d, lengths_d, keys_d, bad_d)

    def _chain_harvest(self, slots, slot_reqs, toks, lengths_h, keys_h,
                       bad_h):
        """Host harvest of a decode chain (per-request isolation — the
        same contract as the vanilla chained step's harvest loop)."""
        for i, (slot, req) in enumerate(zip(slots, slot_reqs)):
            if req.done and req.slot is None:
                continue  # finished elsewhere this step; slot freed
            if req.slot != slot:
                continue  # preempted mid-step; chain row is garbage
            try:
                if self._fi is not None:
                    if self._fi.fire("step-exception", rid=req.rid):
                        raise InjectedFault(
                            f"injected step fault (rid {req.rid})")
                    if self._fi.fire("nan-logits", rid=req.rid):
                        raise NumericsError(
                            "injected non-finite logits", rid=req.rid)
                if bad_h[i]:
                    raise NumericsError(
                        "non-finite logits in decode chain", rid=req.rid)
                self._harvest(req, toks[i])
                self._last_tok[slot] = int(toks[i, -1])
                self.lengths[slot] = int(lengths_h[i])
                self._keys[slot] = keys_h[i]
                if req.done:
                    del self._active[slot]
                    self._free_slot(slot)
                    # clearing the binding makes the done-and-unbound
                    # guard above skip this request's rows in any LATER
                    # chain of a multi-step round trip (ISSUE 12)
                    req.slot = None
            except RequestError as e:
                self._fail_request(req, e)
            except Exception as e:
                self._fail_request(req, self._wrap_step_fault(e, req))

    def _disagg_step(self):
        """Prefill/decode role disaggregation (ISSUE 11 tentpole): one
        scheduling step dispatches the PREFILL-ROLE program (the mixed
        chunk step over mid-prompt slots — streaming each prompt
        ``prefill_chunk`` tokens into the shared pool) and the
        DECODE-ROLE chain (depth-k over fully-prefilled slots)
        back-to-back, then harvests both with ONE blocking fetch.

        Versus the plain mixed step — which locks every decoding slot to
        ONE token per host round trip while any prompt streams — decode
        slots keep their deep chains (k·chunk_size tokens per round
        trip) while long prompts trickle in beside them: the
        DistServe/vLLM prefill-decode separation, in-process, with the
        cache-coordinator's shared (possibly TP-sharded) pool as the
        page handoff instead of a cross-worker KV transfer. A prompt
        whose final chunk lands this step emits its first token here
        and joins the decode-role batch at the very next boundary —
        that handoff is the "stream finished KV pages to the decode
        batch" edge, and prefix-cache hits ride it too (spliced pages
        skip the prefill role entirely).

        Token streams are identical to the mixed step's (and so to the
        single-chip engine's): per-token computation and key burns are
        unchanged, only WHICH program advances a slot differs —
        asserted by tests/test_tp_serving.py across greedy/sampled/
        cache/chaos scenarios."""
        chunk = self.prefill_chunk
        self._bind_chunked()
        if not self._active:
            if self._queue:
                self._note_stall()
            return
        self._stall_steps = 0
        dec = [s for s in sorted(self._active)
               if s not in self._chunk_left]
        k = 1
        if dec:
            # chain depth over the decode-role batch only (the useful-
            # tokens-per-round-trip maximizer, with the eos turnover
            # clamp — same policy as _chain_depth, scoped to dec slots)
            rem = [self._active[s].max_new_tokens
                   - len(self._active[s].tokens) for s in dec]
            kmax = self.max_chain
            if self._queue and self.eos_id is not None:
                kmax = min(kmax, max(1, -(-min(rem) // self.chunk_size)))
            cost = self._boundary_cost_chunks()
            best_k, best_u = 1, -1.0
            kk = 1
            while kk <= kmax:
                useful = sum(min(r, kk * self.chunk_size) for r in rem)
                u = useful / (cost + kk)
                if u > best_u:
                    best_k, best_u = kk, u
                kk *= 2
            k = best_k

        def target(slot, req, kk):
            left = self._chunk_left.get(slot)
            if left is not None:
                return int(self.lengths[slot]) + min(left.size, chunk)
            return self._alloc_len(req, kk)

        # role-aware page reservation: chunk slots need one chunk, chain
        # slots k*chunk_size — the shared shrink→preempt→fail ladder
        # halves k under pressure before anyone is evicted
        k = self._reserve_step_pages(k, target)
        if not self._active:
            return
        k = max(1, k)
        pre = [s for s in sorted(self._active) if s in self._chunk_left]
        dec = [s for s in sorted(self._active)
               if s not in self._chunk_left]
        mixed_d = self._mixed_dispatch(pre) if pre else None
        chain = self._chain_dispatch(dec, k) if dec else None
        # ---- single harvest fence for both roles ----
        handles = []
        if mixed_d is not None:
            handles += list(mixed_d[2:])
        if chain is not None:
            handles += list(chain[2:])
        fetched = jax.device_get(tuple(handles))
        off = 0
        if mixed_d is not None:
            tok, keys_h, bad_h = (np.asarray(a) for a in fetched[:3])
            self._mixed_harvest(mixed_d[0], mixed_d[1], tok, keys_h,
                                bad_h)
            off = 3
        if chain is not None:
            toks, lengths_h, keys_h, bad_h = (
                np.asarray(a) for a in fetched[off:off + 4])
            self._chain_harvest(chain[0], chain[1], toks, lengths_h,
                                keys_h, bad_h)

    def step(self, n: Optional[int] = None) -> int:
        """One scheduling round trip. NEVER raises (ISSUE 6): request-
        scoped faults fail the one request (terminal FAILED with a
        taxonomy reason) inside ``_chained_step``/``_spec_step``'s
        per-request isolation blocks; anything that escapes them is an
        engine-scoped fault handled by ``_recover_step_fault`` —
        requeue-all recompute + pool reset + watchdog degradation.

        ``n`` (default ``Engine(multi_step=)``) is the multi-step budget
        (ISSUE 12): in pure-decode phases up to ``n`` decode iterations
        dispatch back-to-back and harvest behind ONE blocking fetch;
        phases that need per-iteration host decisions (admission waves,
        mixed chunk scheduling, spec drafting) run exactly one iteration
        regardless. Token streams are bit-identical for every ``n``.
        Returns the number of live requests remaining (queued + active)."""
        t0 = time.perf_counter()
        if self._watchdog.quarantined:
            # fail-stop on proven corruption (ISSUE 14): a quarantined
            # engine must not mint another token through weights its own
            # audit proved corrupt — silence is recoverable (the router
            # migrates stalled streams via resume-from-emitted, every
            # delivered token predates the corruption), a wrong token is
            # not. Requests stay live so the migration journal sees them.
            return len(self._queue) + len(self._active)
        if self._fi is not None and self._fi.fire("slow-step"):
            time.sleep(self._fi.param("slow-step", "delay_ms", 20.0) / 1e3)
        if self._has_deadlines:
            self._expire_deadlines()
        budget = self.multi_step if n is None else max(1, int(n))
        batched = 1
        try:
            # KV-tier completions land at the step boundary (ISSUE 15):
            # even a step that admits nothing applies finished spills/
            # promotions, so the tier converges while the engine decodes
            self._cache.drain_tier()
            if self._wants_mixed():
                if self.disaggregate:
                    self._disagg_step()
                else:
                    self._mixed_step()
            elif self._spec is not None and self._spec_enabled:
                self._spec_step()
            elif budget > 1 and self._active and not self._queue:
                batched = self._multi_chained_step(budget)
            else:
                self._chained_step(t0)
            self._watchdog.note_step_ok()
            if self._integrity is not None:
                # online SDC audits (ISSUE 14): weight-shard probe on
                # idle steps, shadow recompute every N — host-side,
                # never raises (detections route through quarantine /
                # _fail_request inside the sentinel)
                self._integrity.on_step()
        except Exception as e:
            self._recover_step_fault(e)
        if self._moe_stats_n:
            # router-stats handles fold at the step boundary: their
            # producing programs were fenced by the harvest above, so
            # this never blocks on in-flight compute
            self._drain_moe_stats()
        if self._m is not None:
            self._m.steps_per_roundtrip.observe(batched)
            self._m.step_seconds.observe(time.perf_counter() - t0)
            self._m.active_slots.set(len(self._active))
            self._m.queue_depth.set(len(self._queue))
            self._m.pages_in_use.set(
                self.num_pages - 1 - len(self._free_pages))
            if self._pcache is not None:
                self._m.pc_pages.set(self._pcache.n_pages)
        if _TRACER.enabled:
            # retroactive step span: start + duration are both known
            # here, so no open-span bookkeeping rides the hot path
            _TRACER.complete(
                "engine.step", "engine",
                time.time() - (time.perf_counter() - t0),
                time.perf_counter() - t0,
                active=len(self._active), queued=len(self._queue),
                batched=batched)
        return len(self._queue) + len(self._active)

    def _recover_step_fault(self, exc: BaseException):
        """Engine-scoped fault recovery (a compiled dispatch died, or the
        step's host spine raised with bookkeeping mid-commit). Never
        re-raises. The recompute policy generalizes preemption: every
        active request requeues (front of queue, retry-bounded) with its
        live PRNG key, and the page pool is rebuilt from scratch —
        donated buffers may be dead after a failed dispatch, and their
        content is fully recomputable from host-side token history. The
        watchdog counts the fault; repeated faults degrade the engine
        (spec→vanilla, then admission cap halved) instead of killing it."""
        self._watchdog.note_step_fault(exc)
        if _TRACER.enabled:
            # flight recorder (ISSUE 18): the ring holds the last N
            # spans/harvests before this fault — dump the postmortem
            # BEFORE recovery rewrites the scheduler state
            _TRACER.instant("engine.step_fault", "fault",
                            error=type(exc).__name__, msg=str(exc)[:200])
            _flight_record(f"step-fault-{type(exc).__name__}")
        if self._m is not None:
            self._m.recoveries.inc()
        for slot in sorted(self._active):
            req = self._active.pop(slot)
            req._key = self._keys[slot].copy()
            req.slot = None
            self._requeue(req)
        # admission-wave/pre-admitted requests whose prefill was in
        # flight live only in the failed step's locals — without this
        # they would vanish from the engine entirely (their standalone
        # page rows die with the pool reset below, which is fine:
        # recompute policy). The _queue check covers a fault landing
        # AFTER the wave committed to _active: the loop above already
        # requeued those, and a double insert would duplicate the stream
        for req, *_ in self._pending_inflight:
            if not req.done and req not in self._queue:
                self._requeue(req)
        self._pending_inflight = []
        # router-stats handles of the failed step's dispatches are dead
        # with their programs; the requeued work re-counts on recompute
        self._moe_pending = []
        self._reset_pool()

    def _chained_step(self, t0):
        """The vanilla scheduling iteration: dispatch the admission
        prefill AND the decode chain back-to-back (the chain's inputs
        splice the prefill's device outputs, so freshly admitted requests
        decode in the same step), then harvest EVERYTHING with a single
        blocking fetch. One host round trip per step instead of the old
        two — admission never stalls the decode pipeline (VERDICT r4 #2).
        With ``prefill_chunk`` set the mixed step owns admission (``step``
        routes there whenever the queue is non-empty), so this path runs
        pure decode chains."""
        if self.prefill_chunk is None:
            admits, pre_tok, pre_keys, pre_bad = self._admit_dispatch()
        else:
            admits, pre_tok, pre_keys, pre_bad = [], None, None, None
        chain = None
        if self._active:
            self._stall_steps = 0
            # pick a chain depth, then allocate pages for the whole chain;
            # under pool pressure shrink the chain before preempting anyone
            # (bounded), before failing the lone unservable request
            k = self._reserve_step_pages(
                self._chain_depth(),
                lambda slot, req, kk: self._alloc_len(req, kk))
        if self._active:
            # compact active slots into a pow2 bucket: per-token cost
            # follows load, not max_slots capacity
            slots = sorted(self._active)
            slot_reqs = [self._active[s] for s in slots]
            n = len(slots)
            nb = _pow2ceil(n)
            if self._m is not None:
                self._m.chain_depth_at(k).inc()
                self._m.decode_batch.observe(n)
            tables_c = np.zeros((nb, self.max_pages_per_seq), np.int32)
            lengths_c = np.zeros((nb,), np.int32)
            last_c = np.zeros((nb,), np.int32)
            temps_c = np.zeros((nb,), np.float32)
            keys_c = np.zeros((nb, 2), np.uint32)
            tables_c[:n] = self.tables[slots]
            lengths_c[:n] = self.lengths[slots]
            last_c[:n] = self._last_tok[slots]
            temps_c[:n] = self._temps[slots]
            keys_c[:n] = self._keys[slots]
            last_in = jnp.asarray(last_c)
            keys_in = jnp.asarray(keys_c)
            if admits:
                # admitted slots' first token / key state live ONLY on
                # device (prefill outputs): splice them into the chain
                # inputs with a tiny scatter — still no host sync
                row_of = {s: i for i, s in enumerate(slots)}
                nba = int(pre_tok.shape[0])
                rows = np.full((nba,), nb, np.int32)  # OOB pads drop
                for i, (_, slot, *_rest) in enumerate(admits):
                    rows[i] = row_of.get(slot, nb)  # preempted → drop
                last_in, keys_in = _patch_rows(
                    last_in, keys_in, jnp.asarray(rows), pre_tok,
                    pre_keys)
            sampling = bool(np.any(temps_c > 0.0))
            fresh = (nb, k, sampling) not in self._decode_fns
            decode = self._get_decode(nb, k, sampling)
            # the whole chain is ONE compiled scan: one dispatch; the ONLY
            # blocking fetch of the step happens below and covers the
            # prefill results too
            toks_d, pages, lengths_d, keys_d, bad_d, *ex = decode(
                self._params, self._pages_flat(), jnp.asarray(tables_c),
                jnp.asarray(lengths_c), last_in,
                jnp.asarray(temps_c), keys_in)
            self._set_pages(pages)
            self._note_moe_stats(ex)
            chain = (slots, slot_reqs, nb, k, fresh, toks_d, lengths_d,
                     keys_d, bad_d)
            # queue heads whose slots this chain will free prefill NOW,
            # in the chain's shadow
            pending, pend_tok, pend_keys, pend_bad = self._preadmit_dispatch(
                k, exclude=[r for r, *_ in admits])
            # registered for step-fault recovery: pending requests live
            # outside queue AND active until _activate_pending commits
            self._pending_inflight = pending
        else:
            if self._queue and not admits:
                # queued but nothing active and no admission possible:
                # tolerated briefly, then the queue head is shed
                # (pre-ISSUE-6 this raised out of step())
                self._note_stall()
            pending, pend_tok, pend_keys, pend_bad = [], None, None, None
        # ---- single harvest fence for prefill + chain + pre-admission ----
        fetched = jax.device_get((
            pre_tok, pre_keys, pre_bad, pend_tok, pend_keys, pend_bad,
            *(chain[5:] if chain else ())))
        if admits:
            self._harvest_admits(admits, fetched[0], fetched[1], fetched[2])
        if chain:
            slots, slot_reqs, nb, k, fresh, *_ = chain
            toks = np.asarray(fetched[6])  # [nb, k*chunk]
            lengths_h = np.asarray(fetched[7])
            keys_h = np.asarray(fetched[8])
            bad_h = np.asarray(fetched[9])
            for i, (slot, req) in enumerate(zip(slots, slot_reqs)):
                if req.done and req.slot is None:
                    continue  # finished at prefill harvest; slot freed
                if req.slot != slot:
                    continue  # preempted mid-step; chain row is garbage
                try:
                    if self._fi is not None:
                        if self._fi.fire("step-exception", rid=req.rid):
                            raise InjectedFault(
                                f"injected step fault (rid {req.rid})")
                        if self._fi.fire("nan-logits", rid=req.rid):
                            raise NumericsError(
                                "injected non-finite logits", rid=req.rid)
                    if bad_h[i]:
                        raise NumericsError(
                            "non-finite logits in decode chain",
                            rid=req.rid)
                    self._harvest(req, toks[i])
                    self._last_tok[slot] = int(toks[i, -1])
                    self.lengths[slot] = int(lengths_h[i])
                    self._keys[slot] = keys_h[i]
                    if req.done:
                        del self._active[slot]
                        self._free_slot(slot)
                except RequestError as e:
                    self._fail_request(req, e)
                except Exception as e:
                    # per-request isolation: ONE request's harvest going
                    # wrong must never take down its batchmates
                    self._fail_request(req, self._wrap_step_fault(e, req))
            if pending:
                self._activate_pending(pending, fetched[3], fetched[4],
                                       fetched[5])
            self._pending_inflight = []
            if not admits and not pending and not fresh:
                # pure-decode step on a warm program: a clean T(k) sample
                # for the measured dispatch-cost ratio (a fresh compile's
                # trace/cache-load seconds would poison the fit)
                self._observe_chain_time(nb, k, time.perf_counter() - t0)

    def _multi_chained_step(self, budget: int) -> int:
        """Multi-step scheduling fast path (ISSUE 12 tentpole): up to
        ``budget`` chained-decode iterations per host round trip.

        Engages only from ``step()`` when the round is PURE DECODE —
        active slots, empty queue, spec off, no prompt mid-chunk — the
        phase where every iteration would otherwise pay the full host
        round trip (pack, dispatch, fetch, harvest) for identical
        scheduling decisions. The same compiled (bucket, depth) decode
        program dispatches ``budget`` times back-to-back with its device
        outputs (pages, lengths, keys, last token) feeding the next
        dispatch — no host fetch between — and ONE ``device_get`` fence
        harvests every chain in submission order.

        Bit-identical to sequential ``step()`` calls by construction:

        * per-row computation is the untouched decode program; chaining
          N dispatches computes exactly what N sequential steps compute
          (the host fetch/re-upload between steps is value-preserving);
        * the harvest walks chains in order through ``_chain_harvest``'s
          per-request isolation blocks — eos/budget truncation, NaN
          guards, and fault-injection points fire per request per chain
          exactly as they do per step;
        * a request finishing (or failing) at chain i frees its slot
          there; its rows in chains i+1.. are garbage the harvest guards
          skip — the same discard path as chain overshoot, with writes
          confined to pages the slot owned (released on free);
        * once the active set drains the harvest EARLY-EXITS, discarding
          the remaining chains wholesale.

        Page reservation covers all ``budget`` chains up front; under
        pool pressure the budget halves BEFORE the shrink→preempt→fail
        ladder can evict anyone a single step wouldn't have (and even a
        preemption keeps streams identical — recompute policy). Returns
        the number of iterations actually harvested."""
        self._stall_steps = 0
        k = self._chain_depth()
        # cap the budget at the work that exists: chains past every
        # request's remaining budget would be pure garbage compute
        max_rem = max(req.max_new_tokens - len(req.tokens)
                      for req in self._active.values())
        budget = max(1, min(budget, -(-max_rem // (k * self.chunk_size))))

        def need_for(b):
            tot = 0
            for slot, req in self._active.items():
                have = int(np.count_nonzero(self.tables[slot]))
                want = min(int(self.lengths[slot]) + b * k * self.chunk_size,
                           req.prompt.size + req.max_new_tokens + 1)
                tot += max(0, self._pages_needed(want) - have)
            return tot

        while budget > 1 and need_for(budget) > self._available_pages():
            budget //= 2
        k = self._reserve_step_pages(
            k, lambda slot, req, kk: min(
                int(self.lengths[slot]) + kk * budget * self.chunk_size,
                req.prompt.size + req.max_new_tokens + 1))
        if not self._active:
            return 1
        k = max(1, k)
        slots = sorted(self._active)
        slot_reqs = [self._active[s] for s in slots]
        n = len(slots)
        nb = _pow2ceil(n)
        if self._m is not None:
            self._m.decode_batch.observe(n)
        tables_c = np.zeros((nb, self.max_pages_per_seq), np.int32)
        lengths_c = np.zeros((nb,), np.int32)
        last_c = np.zeros((nb,), np.int32)
        temps_c = np.zeros((nb,), np.float32)
        keys_c = np.zeros((nb, 2), np.uint32)
        tables_c[:n] = self.tables[slots]
        lengths_c[:n] = self.lengths[slots]
        last_c[:n] = self._last_tok[slots]
        temps_c[:n] = self._temps[slots]
        keys_c[:n] = self._keys[slots]
        sampling = bool(np.any(temps_c > 0.0))
        decode = self._get_decode(nb, k, sampling)
        tables_j = jnp.asarray(tables_c)
        temps_j = jnp.asarray(temps_c)
        pages = self._pages_flat()
        lengths_in = jnp.asarray(lengths_c)
        last_in = jnp.asarray(last_c)
        keys_in = jnp.asarray(keys_c)
        chains = []
        for _ in range(budget):
            toks_d, pages, lengths_in, keys_in, bad_d, *ex = decode(
                self._params, pages, tables_j, lengths_in, last_in,
                temps_j, keys_in)
            self._note_moe_stats(ex)
            # the chain-to-chain handoff stays ON DEVICE: the next
            # chain's last-token input is the previous chain's final
            # column (statically gated by the analyze registry's
            # multi_step_decode twin at tp>1 — shards carry locally)
            last_in = _last_col(toks_d)
            chains.append((toks_d, lengths_in, keys_in, bad_d))
            if self._m is not None:
                self._m.chain_depth_at(k).inc()
        self._set_pages(pages)
        # ---- the round trip's ONLY blocking fence ----
        fetched = jax.device_get(tuple(h for c in chains for h in c))
        done = 0
        for i in range(budget):
            toks, lengths_h, keys_h, bad_h = (
                np.asarray(a) for a in fetched[4 * i:4 * i + 4])
            self._chain_harvest(slots, slot_reqs, toks, lengths_h,
                                keys_h, bad_h)
            done = i + 1
            if not self._active:
                break  # early exit: everyone finished/failed — the
                # remaining chains' outputs are overshoot, discarded
        return done

    # ------------------------------------------------ speculative decoding
    def _spec_step(self):
        """One spec-decode scheduling iteration (ISSUE 5 tentpole):
        admit (blocking — drafting needs the host-side token history of
        every active request anyway), let the drafter propose up to k
        tokens per request, score ALL k+1 positions in ONE verify
        forward through the paged decode path, then accept — token-exact
        prefix matching for greedy, distribution-preserving rejection
        sampling for temperature>0 (reusing the per-request key state) —
        and roll rejected rows back via ``_trim_pages`` so the
        preemption/eviction invariants hold. Each step lands 1..k+1
        tokens per request; every metric normalizes by the ACTUAL count
        (see ``_EngineMetrics.on_harvest``), and spec steps never feed
        ``_observe_chain_time`` — the chain-depth calibration stays a
        vanilla-only fit that varying acceptance cannot skew.

        Drafter faults (ISSUE 6): a drafter that raises — or is
        fault-injected via ``drafter-corruption`` — degrades THIS step to
        zero drafts, and a zero-draft verify is exactly a vanilla decode
        step, so greedy output is unchanged. The drafter's private cache
        resets so its next proposal re-syncs from the host-side token
        history (slot reconciliation after failure), and the watchdog
        counts faults toward disabling spec outright."""
        t0 = time.perf_counter()
        spec = self._spec
        self._admit()
        if not self._active:
            if self._queue:
                self._note_stall()
            return
        self._stall_steps = 0
        k = spec.k
        # allocate the k+1-row verify block for every slot, preempting
        # the longest request under pool pressure exactly like the
        # vanilla depth-1 chain (writes past a request's own budget cap
        # route to the trash page via the zero table entries)
        self._reserve_step_pages(
            1, lambda slot, req, _kk: min(
                int(self.lengths[slot]) + k + 1,
                req.prompt.size + req.max_new_tokens + 1))
        if not self._active:
            return
        slots = sorted(self._active)
        reqs = [self._active[s] for s in slots]
        n = len(slots)
        nb = _pow2ceil(n)
        want = [spec.controller.draft_len(r) for r in reqs]
        try:
            if self._fi is not None and self._fi.fire("drafter-corruption"):
                if self._fi.param("drafter-corruption", "corrupt", 0.0):
                    # corrupt the PROPOSALS, not the drafter: acceptance
                    # only ever keeps tokens matching the target, so this
                    # proves rejection absorbs garbage drafts
                    drafts, dlen = spec.drafter.propose(
                        self, slots, reqs, want, k)
                    drafts = ((np.asarray(drafts) + 1)
                              % self.cfg.vocab_size).astype(np.int32)
                else:
                    raise InjectedFault("injected drafter fault")
            else:
                drafts, dlen = spec.drafter.propose(self, slots, reqs,
                                                    want, k)
            self._watchdog.note_drafter_ok()
        except Exception as e:
            # drafter fault fallback: draft NOTHING this step (vanilla-
            # equivalent), reset the drafter's private cache, let the
            # watchdog decide whether spec should stay on
            spec.note_drafter_fault(e)
            self._watchdog.note_drafter_fault()
            drafts = np.zeros((nb, k), np.int32)
            dlen = np.zeros((n,), np.int32)
        tables_c = np.zeros((nb, self.max_pages_per_seq), np.int32)
        lengths_c = np.zeros((nb,), np.int32)
        last_c = np.zeros((nb,), np.int32)
        temps_c = np.zeros((nb,), np.float32)
        keys_c = np.zeros((nb, 2), np.uint32)
        dlen_c = np.zeros((nb,), np.int32)
        tables_c[:n] = self.tables[slots]
        lengths_c[:n] = self.lengths[slots]
        last_c[:n] = self._last_tok[slots]
        temps_c[:n] = self._temps[slots]
        keys_c[:n] = self._keys[slots]
        dlen_c[:n] = dlen
        sampling = bool(np.any(temps_c > 0.0))
        verify = spec.get_verify(nb, sampling)
        if self._m is not None:
            self._m.decode_batch.observe(n)
            # the verify program rides the fused verify/suffix slab
            # attention path (ISSUE 9) — count the dispatch
            self._m.slab_dispatch.labels(path="verify").inc()
        # ONE dispatch scores every draft position; the fetch below is
        # the step's only blocking sync besides admission
        toks_d, nem_d, len_d, keys_d, bad_d, pages = verify(
            self._params, self._pages_flat(), jnp.asarray(tables_c),
            jnp.asarray(lengths_c), jnp.asarray(last_c),
            jnp.asarray(drafts), jnp.asarray(dlen_c),
            jnp.asarray(temps_c), jnp.asarray(keys_c))
        self._set_pages(pages)
        toks, nem, lengths_h, keys_h, bad_h = (
            np.asarray(a) for a in jax.device_get(
                (toks_d, nem_d, len_d, keys_d, bad_d)))
        step_proposed = step_accepted = 0
        for i, (slot, req) in enumerate(zip(slots, reqs)):
            try:
                if self._fi is not None:
                    if self._fi.fire("step-exception", rid=req.rid):
                        raise InjectedFault(
                            f"injected step fault (rid {req.rid})")
                    if self._fi.fire("nan-logits", rid=req.rid):
                        raise NumericsError(
                            "injected non-finite logits", rid=req.rid)
                if bad_h[i]:
                    raise NumericsError(
                        "non-finite logits in verify block", rid=req.rid)
                n_emit = int(nem[i])
                accepted = n_emit - 1  # drafts accepted (bonus is free)
                consumed = self._harvest(req, toks[i, :n_emit].tolist())
                spec.note(req, proposed=int(dlen[i]), accepted=accepted,
                          landed=consumed)
                step_proposed += int(dlen[i])
                step_accepted += min(accepted, int(dlen[i]))
                if req.done:
                    # eos/budget mid-block: _harvest truncated the
                    # accepted block at the boundary; freeing the slot
                    # recycles every page — INCLUDING rows past the eos —
                    # the same step (ISSUE 5 satellite)
                    del self._active[slot]
                    self._free_slot(slot)
                    req.slot = None
                    spec.drafter.release(slot)
                    spec.controller.forget(req)
                else:
                    # keep exactly the accepted prefix: lengths rolls
                    # back to base + 1 + accepted (computed in-program)
                    # and the headroom pages — rejected draft rows
                    # included — return to the pool
                    self.lengths[slot] = int(lengths_h[i])
                    self._last_tok[slot] = int(toks[i, n_emit - 1])
                    self._keys[slot] = keys_h[i]
                    self._trim_pages(slot, int(lengths_h[i]))
            except RequestError as e:
                self._fail_request(req, e)
            except Exception as e:
                self._fail_request(req, self._wrap_step_fault(e, req))
        spec.observe_step(time.perf_counter() - t0)
        # acceptance-collapse detection: a full window of near-zero
        # acceptance means drafting burns a dispatch per step for
        # nothing — the watchdog degrades spec→vanilla, probes back later
        self._watchdog.note_acceptance(step_proposed, step_accepted)

    def run(self, requests=None) -> List[Request]:
        """Serve ``requests`` (or whatever is queued) to completion.
        A quarantined engine (integrity fail-stop, ISSUE 14) returns
        early with work still live — ``step()`` is a no-op there, and
        spinning on it would never terminate; the multi-replica router
        is the layer that finishes those streams elsewhere."""
        if requests:
            done = list(requests)
        else:
            done = list(self._queue)
        while self.step():
            if self._watchdog.quarantined:
                break
        return done


def bench_engine_decode(cfg, on_tpu):
    """Driver-visible paged-serving benchmark, per cache/weight config:

    * ``*_decode_tokens_per_sec`` — steady-state full-occupancy decode:
      all slots admitted, compiled programs warm, timed from after
      admission to completion (the r3-comparable metric; chaining means
      this window is typically ONE host fetch).
    * ``*_serve_tokens_per_sec`` — a mixed-length, mixed-budget workload
      served end-to-end (admission waves, slot churn, re-admission)
      after an identical warmup pass compiled every bucket.
    * ``paged_serve_first_wave_tokens_per_sec`` (bf16 config only) — the
      SAME mixed workload's very first pass in this process, jit tracing
      and compiles included. With the persistent compilation cache
      enabled (bench main does) a restarted server pays cache loads, not
      multi-second Mosaic compiles — this line is what a deployment's
      cold start actually feels like (VERDICT r4 #5/weak #7).
    * ``paged_serve_chunked_*`` (bf16 config only, ISSUE 9) — the same
      mixed workload through a chunked-prefill engine
      (``prefill_chunk``): steady-state rate, plus the RESTART first
      wave — a fresh Engine instance whose first pass pays jit tracing
      and compilation-cache loads but no cold compiles (an identical
      engine ran once before, standing in for the previous server
      process; the unchunked first-wave line above keeps the true
      process-cold number). Chunking collapses the prompt-side compile
      surface to ONE fixed-shape mixed program, so
      ``paged_serve_chunked_first_wave_frac`` (first wave / chunked
      steady serve) gates ≥ 0.5 — the ISSUE 9 first-wave criterion.

    Configs: bf16 weights + bf16 cache (``paged``), bf16 + int8 KV pages
    (``paged_int8``), int4 packed weights + int8 KV pages
    (``paged_int4w`` — VERDICT r4 #3: the full serving quantization
    stack).
    """
    from ..models.gpt import GPTForCausalLM

    out = {}
    for wq, cache_q, key in ((None, False, "paged"),
                             (None, True, "paged_int8"),
                             ("weight_only_int4", True, "paged_int4w")):
        model = GPTForCausalLM(cfg)
        model.eval()
        model.bfloat16()
        if wq is not None:
            from ..nn.quant import quantize_for_decode

            _, swapped = quantize_for_decode(model, algo=wq)
            if not swapped:
                continue
        slots = 8 if on_tpu else 2
        new_tokens = 256 if on_tpu else 8
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, cfg.vocab_size,
                                (int(rng.integers(24, 120)),))
                   for _ in range(slots)]

        # The engine's compiled programs are cached per instance and its
        # allocator state fully resets when a run drains, so warmup and
        # timed passes reuse ONE engine (identical request schedules →
        # identical bucket shapes → every timed dispatch hits the cache).
        eng = Engine(model, max_slots=slots,
                     num_pages=(slots + 2) * cfg.max_position // 16 + 1,
                     page_size=16, chunk_size=32 if on_tpu else 4,
                     max_chain=8 if on_tpu else 2,
                     quantized_cache=cache_q)

        def mixed_requests():
            r = np.random.default_rng(7)
            return [eng.add_request(
                r.integers(0, cfg.vocab_size, (int(r.integers(24, 120)),)),
                int(r.integers(new_tokens // 2, new_tokens)))
                for _ in range(2 * slots)]

        # -- cold start: the bf16 config's FIRST pass, compiles included
        if wq is None and not cache_q:
            reqs = mixed_requests()
            t0 = time.perf_counter()
            eng.run()
            dt = time.perf_counter() - t0
            out["paged_serve_first_wave_tokens_per_sec"] = round(
                sum(len(r.tokens) for r in reqs) / dt, 1)

        # -- steady state: same-budget requests, full occupancy ----------
        def steady_requests():
            return [eng.add_request(p, new_tokens) for p in prompts]

        # TWO warmup passes: the first also calibrates the measured
        # dispatch-cost ratio, which can change the chain-depth choice —
        # the second compiles any newly selected (bucket, depth) program
        # so the timed window is guaranteed warm
        for _ in range(2):
            steady_requests()
            eng.run()
        reqs = steady_requests()
        eng._admit()       # prefill outside the timed window (r3 protocol)
        done0 = sum(len(r.tokens) for r in reqs)
        t0 = time.perf_counter()
        while eng.step():
            pass
        dt = time.perf_counter() - t0
        total = sum(len(r.tokens) for r in reqs) - done0
        out[f"{key}_decode_tokens_per_sec"] = round(total / dt, 1)

        # -- mixed workload, end-to-end (warm run timed) -----------------
        for _ in range(2):             # two passes: see steady warmup
            mixed_requests()
            eng.run()
        # the serve loop crosses several host sync points, so single-shot
        # timing rides the tunnel's RTT jitter — median of 3 runs
        rates = []
        for _ in range(3 if on_tpu else 1):
            reqs = mixed_requests()
            t0 = time.perf_counter()
            eng.run()
            dt = time.perf_counter() - t0
            rates.append(sum(len(r.tokens) for r in reqs) / dt)
        out[f"{key}_serve_tokens_per_sec"] = round(
            sorted(rates)[len(rates) // 2], 1)

        # -- chunked prefill (ISSUE 9, bf16 config only) -----------------
        if wq is None and not cache_q:
            pchunk = 32 if on_tpu else 8
            # the restart wave is SUSTAINED load, not a 20-token blip:
            # the gate compares first-pass rate against steady state, so
            # the wave must be long enough that the one-time restart
            # cost (jit tracing + compilation-cache loads) amortizes the
            # way it does for a real server's first minute. Budgets
            # scale with the platform's token rate (the CPU smoke model
            # decodes 8-token completions; per-request budgets stay
            # under the max_position admission limit on both).
            n_creq = (4 if on_tpu else 16) * slots
            blo, bhi = ((new_tokens, 2 * new_tokens) if on_tpu
                        else (8 * new_tokens, 16 * new_tokens))

            def chunked_engine():
                return Engine(model, max_slots=slots,
                              num_pages=(slots + 2) * cfg.max_position
                              // 16 + 1,
                              page_size=16, chunk_size=32 if on_tpu else 4,
                              max_chain=8 if on_tpu else 2,
                              prefill_chunk=pchunk)

            def chunked_requests(eng):
                r = np.random.default_rng(7)
                return [eng.add_request(
                    r.integers(0, cfg.vocab_size,
                               (int(r.integers(24, 120)),)),
                    int(r.integers(blo, bhi)))
                    for _ in range(n_creq)]

            # warm the compilation cache with a throwaway engine — the
            # "previous server process" of the restart protocol
            warm = chunked_engine()
            chunked_requests(warm)
            warm.run()
            # restart first wave: a FRESH engine's very first pass (jit
            # tracing + cache loads; the mixed program is the only
            # prompt-side shape, so there are no prompt-length buckets
            # left to compile)
            engc = chunked_engine()
            reqs = chunked_requests(engc)
            t0 = time.perf_counter()
            engc.run()
            dt = time.perf_counter() - t0
            first_wave = sum(len(r.tokens) for r in reqs) / dt
            out["paged_serve_chunked_first_wave_tokens_per_sec"] = round(
                first_wave, 1)
            # steady chunked serve: same protocol as the vanilla line
            chunked_requests(engc)
            engc.run()
            rates_c = []
            for _ in range(3 if on_tpu else 1):
                reqs = chunked_requests(engc)
                t0 = time.perf_counter()
                engc.run()
                dt = time.perf_counter() - t0
                rates_c.append(sum(len(r.tokens) for r in reqs) / dt)
            steady_c = sorted(rates_c)[len(rates_c) // 2]
            out["paged_serve_chunked_tokens_per_sec"] = round(steady_c, 1)
            frac = first_wave / steady_c if steady_c else 0.0
            out["paged_serve_chunked_first_wave_frac"] = round(frac, 3)
            out["paged_serve_chunked_first_wave_ok"] = bool(frac >= 0.5)
            out["paged_serve_prefill_chunk"] = pchunk
    return out


def bench_fault_tolerance(cfg, on_tpu):
    """Fault-rate scenario (ISSUE 6 satellite, lands in BENCH_r06): the
    mixed serving workload re-run with injected per-request failures —
    ONE targeted request per pass (1/n_req ≈ 1% at the TPU request
    count) dies at its first harvest via the ``step-exception`` point.
    Gates: steady-state throughput within 10% of the clean run
    (``fault_ratio_ok``) and ZERO whole-engine recoveries
    (``fault_zero_restarts_ok``) — per-request isolation must cost a
    request, never the engine."""
    from ..models.gpt import GPTForCausalLM
    from ..observability import metric_total

    model = GPTForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    slots = 8 if on_tpu else 2
    new_tokens = 128 if on_tpu else 16
    n_req = 100 if on_tpu else 16

    def workload(eng):
        r = np.random.default_rng(11)
        return [eng.add_request(
            r.integers(0, cfg.vocab_size, (int(r.integers(24, 120)),)),
            new_tokens) for _ in range(n_req)]

    def serve(plan):
        eng = Engine(model, max_slots=slots,
                     num_pages=(slots + 2) * cfg.max_position // 16 + 1,
                     page_size=16, chunk_size=32 if on_tpu else 4,
                     max_chain=8 if on_tpu else 2, fault_plan=plan)
        for _ in range(2):  # warm every compiled bucket
            workload(eng)
            eng.run()
        reqs = workload(eng)
        t0 = time.perf_counter()
        eng.run()
        dt = time.perf_counter() - t0
        delivered = sum(len(r.tokens) for r in reqs)
        failed = sum(1 for r in reqs if r.failed)
        return delivered / dt, failed

    rec0 = metric_total("paddle_tpu_engine_recoveries_total")
    clean_rate, _ = serve(None)
    # the timed pass is the third per engine (rids start at 2*n_req).
    # The SECOND warmup pass takes an identical injected failure so the
    # post-failure bucket shapes (odd active counts, changed chain
    # depths) are compiled before the timed window — the criterion
    # measures steady-state fault cost, not a one-off compile.
    warm_rid = n_req + n_req // 2
    target_rid = 2 * n_req + n_req // 2
    fault_rate, failed = serve(
        f"step-exception:rid={warm_rid},at=1;"
        f"nan-logits:rid={target_rid},times=1")
    recoveries = int(
        metric_total("paddle_tpu_engine_recoveries_total") - rec0)
    ratio = fault_rate / clean_rate if clean_rate else 0.0
    return {
        "fault_clean_tokens_per_sec": round(clean_rate, 1),
        "fault_injected_tokens_per_sec": round(fault_rate, 1),
        "fault_throughput_ratio": round(ratio, 3),
        "fault_ratio_ok": bool(ratio >= 0.9),
        "fault_injected_request_rate": round(1.0 / n_req, 3),
        "fault_failed_requests": int(failed),
        "fault_engine_recoveries": recoveries,
        "fault_zero_restarts_ok": recoveries == 0,
    }


def bench_spec_decode(cfg, on_tpu):
    """Speculative decoding on a repeated-structure workload (ISSUE 5):
    prompts tile a short motif, and greedy continuations of a small model
    collapse into repetition — the regime prompt-lookup drafting exploits
    (templated text, code, copied spans in real serving). Reports mean
    accepted tokens per verify step, draft acceptance rate, and measured
    spec ms/token beside the vanilla engine on the SAME workload and
    geometry (the acceptance criterion: ngram accept/step >= 1.5)."""
    from ..models.gpt import GPTForCausalLM

    model = GPTForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    slots = 4 if on_tpu else 2
    new_tokens = 128 if on_tpu else 48
    spec_k = 8

    def workload(eng):
        reqs = []
        r = np.random.default_rng(23)
        for _ in range(2 * slots):
            motif = r.integers(0, cfg.vocab_size, (8,))
            reqs.append(eng.add_request(np.tile(motif, 4), new_tokens))
        return reqs

    out = {}
    for mode in (None, "ngram"):
        eng = Engine(model, max_slots=slots,
                     num_pages=(slots + 2) * cfg.max_position // 16 + 1,
                     page_size=16, chunk_size=8,
                     max_chain=8 if on_tpu else 2,
                     spec=mode, spec_k=spec_k)
        for _ in range(2):  # warm every compiled bucket
            workload(eng)
            eng.run()
        reqs = workload(eng)
        t0 = time.perf_counter()
        eng.run()
        dt = time.perf_counter() - t0
        total = sum(len(r.tokens) for r in reqs)
        key = "vanilla" if mode is None else f"spec_{mode}"
        out[f"{key}_serve_tokens_per_sec"] = round(total / dt, 1)
        if mode is not None:
            stats = eng._spec.stats()
            out[f"spec_{mode}_accept_per_step"] = round(
                stats["accept_per_step"], 3)
            out[f"spec_{mode}_accept_rate"] = round(
                stats["accept_rate"], 3)
            out["decode_spec_ms_per_token"] = round(
                stats["spec_ms_per_token"], 3)
            out["spec_k"] = stats["k"]
    return out


def bench_moe_serving(cfg, on_tpu):
    """MoE serving scenario (ISSUE 17): steady-state decode throughput
    of the tiny MoE llama (8 experts, top-2, 64-wide expert FFs —
    replicated routing, capacity-factor token budget, grouped-expert
    Pallas FFN) against its equal-active-params dense twin (the 128-wide
    tiny MLP: 2 experts * 64 active per token) on the SAME paged
    geometry and workload.

    Gate: dense/MoE decode-rate ratio <= 1.5 — router + sort + grouped
    dispatch must cost less than half again the dense twin's step. The
    comparison is interleaved (moe, dense) rep medians floored at the
    50 ms single-core jitter floor; the CPU smoke host additionally runs
    the grouped kernel in Pallas interpret mode, which the floor keeps
    from reading as model cost. The metrics tail reports the router's
    cumulative behavior: drop fraction (dropped pairs / routed pairs),
    per-expert load imbalance (max/mean), mean router entropy in nats.
    """
    from .. import seed as _seed
    from ..models.llama import (LlamaForCausalLM, tiny_llama_config,
                                tiny_moe_llama_config)

    del cfg  # the block sizes its own twin configs (CPU smoke parity)

    slots = 4 if on_tpu else 2
    new_tokens = 64 if on_tpu else 8
    moe_cfg = tiny_moe_llama_config()

    def build(mcfg):
        _seed(0)
        model = LlamaForCausalLM(mcfg)
        model.eval()
        return Engine(model, max_slots=slots, num_pages=64, page_size=8,
                      chunk_size=4, max_chain=8 if on_tpu else 2,
                      dtype=jnp.float32)

    engines = {"moe": build(moe_cfg), "dense": build(tiny_llama_config())}
    rng = np.random.default_rng(41)
    prompts = [rng.integers(0, moe_cfg.vocab_size,
                            (int(rng.integers(8, 24)),))
               for _ in range(slots)]

    def decode_once(eng):
        reqs = [eng.add_request(p, new_tokens) for p in prompts]
        eng._admit()       # prefill outside the timed window (r3 protocol)
        done0 = sum(len(r.tokens) for r in reqs)
        t0 = time.perf_counter()
        while eng.step():
            pass
        dt = time.perf_counter() - t0
        return sum(len(r.tokens) for r in reqs) - done0, dt

    for eng in engines.values():   # two passes warm every compiled bucket
        decode_once(eng)
        decode_once(eng)
    reps = 3
    toks = {k: 0 for k in engines}
    times = {k: [] for k in engines}
    for _ in range(reps):
        for key, eng in engines.items():      # interleaved rep pairs
            n, dt = decode_once(eng)
            toks[key] += n
            times[key].append(dt)

    floor_s = 0.020 if on_tpu else 0.050
    med = {k: max(float(np.median(v)), floor_s) for k, v in times.items()}
    thr = {k: toks[k] / (med[k] * reps) for k in engines}
    ratio = thr["dense"] / thr["moe"] if thr["moe"] else float("inf")
    stats = engines["moe"].moe_stats()
    ok = ratio <= 1.5 and stats.get("tokens_routed", 0) > 0
    if not ok:
        print(f"WARNING: bench_moe gate failed: dense/moe decode ratio="
              f"{ratio:.3f} (<=1.5), tokens_routed="
              f"{stats.get('tokens_routed', 0)} (>0)")
    return {
        "moe_decode_tokens_per_sec": round(thr["moe"], 1),
        "moe_dense_twin_tokens_per_sec": round(thr["dense"], 1),
        "moe_dense_over_moe_ratio": round(ratio, 3),
        "moe_drop_frac": round(float(stats["drop_frac"]), 4),
        "moe_load_imbalance": round(float(stats["load_imbalance"]), 3),
        "moe_router_entropy_nats": round(float(stats["router_entropy"]), 3),
        "moe_gate_ok": bool(ok),
    }


def bench_prefix_cache(cfg, on_tpu):
    """Prefix-caching scenario (ISSUE 8, lands in BENCH_r08): a templated
    workload — every prompt shares a long system-prompt/few-shot template
    (~90% of its tokens) with a distinct user tail — served cache-on vs
    cache-off, plus a zero-overlap guard run.

    * ``prefix_speedup`` — effective prefill throughput ratio (prompt
      tokens ingested per second over a prefill-dominated workload: tiny
      budgets, so serve time is prefill time). Acceptance: >= 5x at 90%
      overlap on TPU; the CPU gate is looser (cache-on strictly faster
      AND hit rate > 0.8) because interpret-mode XLA narrows the
      flash-vs-gather attention gap the splice removes.
    * ``prefix_zero_overlap_ratio`` — the mixed DISTINCT-prompt workload
      with the cache on vs off: when it never hits, the cache must cost
      < 5% (acceptance: ratio >= 0.95)."""
    from ..models.gpt import GPTForCausalLM

    model = GPTForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    slots = 8 if on_tpu else 2
    if on_tpu:
        template_len, tail_len, budget = 720, 80, 8
        num_pages = (slots + 6) * cfg.max_position // 16 + 1
    else:
        template_len, tail_len, budget = 144, 16, 2
        num_pages = 160
    n_req = 4 * slots
    rng = np.random.default_rng(31)
    template = rng.integers(0, cfg.vocab_size, (template_len,))
    tail_seed = [0]  # distinct tails per request AND per batch

    def make_engine(enable):
        return Engine(model, max_slots=slots, num_pages=num_pages,
                      page_size=16, chunk_size=32 if on_tpu else 4,
                      max_chain=8 if on_tpu else 2, prefix_cache=enable)

    def templated(eng):
        reqs = []
        for _ in range(n_req):
            tail_seed[0] += 1
            r = np.random.default_rng(1000 + tail_seed[0])
            prompt = np.concatenate(
                [template, r.integers(0, cfg.vocab_size, (tail_len,))])
            reqs.append(eng.add_request(prompt, budget))
        return reqs

    def serve(enable):
        eng = make_engine(enable)
        templated(eng)
        eng.run()  # warm every compiled bucket (and seed the cache)
        pc = eng._pcache
        h0, m0 = (pc.hits, pc.misses) if pc is not None else (0, 0)
        reqs = templated(eng)
        t0 = time.perf_counter()
        eng.run()
        dt = time.perf_counter() - t0
        ptoks = sum(r.prompt.size for r in reqs)
        # hit rate over the TIMED pass only: the cold pass's misses (and
        # its pre-admission prefills racing the first registrations) are
        # warmup, not the steady state the criterion gates
        hit_rate = ((pc.hits - h0) / max(1, pc.hits - h0 + pc.misses - m0)
                    if pc is not None else 0.0)
        return ptoks / dt, hit_rate, eng

    off_rate, _, _ = serve(False)
    on_rate, hit_rate, eng_on = serve(True)
    pc = eng_on._pcache
    speedup = on_rate / off_rate if off_rate else 0.0

    # -- zero-overlap guard: distinct prompts, the cache never hits ------
    def distinct(eng):
        tail_seed[0] += 1
        r = np.random.default_rng(5000 + tail_seed[0])
        return [eng.add_request(
            r.integers(0, cfg.vocab_size, (int(r.integers(24, 120)),)),
            32 if on_tpu else 8) for _ in range(2 * slots)]

    def serve_distinct(enable):
        eng = make_engine(enable)
        for _ in range(2):
            distinct(eng)
            eng.run()
        # the serve loop crosses several host syncs — median of 3 runs,
        # same protocol as bench_engine_decode's mixed workload
        rates = []
        for _ in range(3):
            reqs = distinct(eng)
            t0 = time.perf_counter()
            eng.run()
            dt = time.perf_counter() - t0
            rates.append(sum(len(r.tokens) for r in reqs) / dt)
        return sorted(rates)[1]

    zo_off = serve_distinct(False)
    zo_on = serve_distinct(True)
    zo_ratio = zo_on / zo_off if zo_off else 0.0
    ok = (speedup >= 5.0 if on_tpu
          else (speedup > 1.0 and hit_rate > 0.8))
    return {
        "prefix_overlap_frac": round(
            template_len / (template_len + tail_len), 3),
        "prefix_prefill_tokens_per_sec": round(on_rate, 1),
        "prefix_prefill_tokens_per_sec_off": round(off_rate, 1),
        "prefix_speedup": round(speedup, 3),
        "prefix_hit_rate": round(hit_rate, 3),
        "prefix_speedup_ok": bool(ok),
        "prefix_cache_evictions": int(pc.evictions),
        "prefix_zero_overlap_ratio": round(zo_ratio, 3),
        "prefix_zero_overlap_ok": bool(zo_ratio >= 0.95),
    }
