"""Continuous-batching serving engine over the paged KV cache.

Reference capability: the serving loop behind
``paddle/fluid/inference/api/analysis_predictor.cc`` driving
``fused_multi_transformer_op.cu`` decode passes (SURVEY A19 + A3.x) —
request admission, KV cache management, decode scheduling, streaming
output. TPU-first design instead of a C++ executor loop:

* **Slots + pages.** ``max_slots`` sequence slots share one page pool per
  layer (vLLM-style block tables). A finished request's pages recycle
  immediately; physical page 0 is reserved as the trash page idle slots
  write into, so the compiled step needs no active-slot branching.
* **Compiled chunks, host scheduling.** Decode runs ``chunk_size`` steps
  per dispatch as ONE jitted ``lax.scan`` over functional
  ``PagedCacheState`` pytrees (block tables and lengths are traced
  operands — no recompile as requests come and go). The host only runs
  between chunks: harvest tokens, finish/free, admit, top up page
  allocations. On the tunneled single-chip setup one chunk costs one
  dispatch + one fetch, amortizing the round trip over ``chunk_size``
  tokens x ``max_slots`` slots.
* **Prefill buckets.** Prompts are padded to power-of-two buckets and
  prefilled slot-at-a-time through the same model forward (causal flash
  over the padded prompt; ``prefill_valid`` masks the page writes, so a
  handful of compiled prefill programs serve any prompt length).
* **No head-of-line blocking.** Admission fills any free slot while other
  slots keep decoding; short requests drain and recycle their pages while
  long ones continue.

The engine is model-agnostic: anything with the causal-LM cache contract
(``forward(ids, caches=..., time_step=None)`` handling ``PagedCacheState``,
plus ``config`` with num_layers / num_kv_heads / head_dim) serves — GPT and
LLaMA both qualify.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, pause_tape
from ..ops.pallas.paged_attention import PagedCacheState


@dataclass
class Request:
    rid: int
    prompt: np.ndarray  # [S] int32
    max_new_tokens: int
    on_token: Optional[Callable] = None  # streaming callback(list[int])
    tokens: List[int] = field(default_factory=list)  # generated tokens
    done: bool = False
    slot: Optional[int] = None


class Engine:
    """Continuous-batching engine; see module docstring."""

    def __init__(self, model, max_slots=8, num_pages=512, page_size=16,
                 chunk_size=16, eos_id: Optional[int] = None,
                 dtype=jnp.bfloat16, quantized_cache=False):
        cfg = model.config
        self.model = model
        self.cfg = cfg
        self.max_slots = max_slots
        self.page_size = page_size
        self.chunk_size = chunk_size
        self.eos_id = eos_id
        self.quantized = bool(quantized_cache)
        self.max_pages_per_seq = cfg.max_position // page_size
        self.num_pages = num_pages
        n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
        store = jnp.int8 if self.quantized else dtype
        # slab page layout [P, page_size, Hkv*D] (contiguous 128-lane rows;
        # see paged_slab_decode_attention for why this beats per-head pages)
        shape = (num_pages, page_size, n_kv * cfg.head_dim)
        self.k_pages = [jnp.zeros(shape, store) for _ in range(cfg.num_layers)]
        self.v_pages = [jnp.zeros(shape, store) for _ in range(cfg.num_layers)]
        if self.quantized:
            # per-token-per-head bf16 scales packed into 128-lane pages
            # (k at lanes [0, Hkv), v at [Hkv, 2Hkv))
            sshape = (num_pages, page_size, 128)
            self.scale_pages = [jnp.zeros(sshape, jnp.bfloat16)
                                for _ in range(cfg.num_layers)]
        else:
            self.scale_pages = [None] * cfg.num_layers
        # host-side allocator state; page 0 reserved as the trash page
        self.tables = np.zeros((max_slots, self.max_pages_per_seq), np.int32)
        self.lengths = np.zeros((max_slots,), np.int32)
        self._free_pages = list(range(num_pages - 1, 0, -1))
        self._free_slots = list(range(max_slots - 1, -1, -1))
        self._queue: List[Request] = []
        self._active: Dict[int, Request] = {}  # slot -> request
        self._last_tok = np.zeros((max_slots,), np.int32)
        self._next_rid = 0
        self._decode_fn = None
        self._prefill_fns = {}
        self._params = [p._data for _, p in model.named_parameters()]

    # ------------------------------------------------------------- requests
    def add_request(self, prompt, max_new_tokens, on_token=None) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        # chunked decode can overshoot a finished request by up to one chunk
        # before the host harvests — leave that headroom below max_position
        limit = self.cfg.max_position - self.chunk_size - 1
        if prompt.size + max_new_tokens > limit:
            import warnings

            clamped = max(0, limit - prompt.size)
            warnings.warn(
                f"max_new_tokens clamped {max_new_tokens} -> {clamped}: "
                f"prompt ({prompt.size}) + generation must stay under "
                f"max_position - chunk_size ({limit})", RuntimeWarning,
                stacklevel=2)
            max_new_tokens = clamped
        # fail fast on a request that could NEVER be served — otherwise the
        # scheduler would spin forever waiting for pages that cannot exist
        worst = self._pages_needed(prompt.size + max_new_tokens
                                   + self.chunk_size)
        if worst > min(self.max_pages_per_seq, self.num_pages - 1):
            raise ValueError(
                f"request needs up to {worst} pages but the pool/table caps "
                f"at {min(self.max_pages_per_seq, self.num_pages - 1)} — "
                "grow num_pages or shrink the request")
        req = Request(self._next_rid, prompt, max_new_tokens, on_token)
        self._next_rid += 1
        self._queue.append(req)
        return req

    # ------------------------------------------------------------ allocator
    def _pages_needed(self, length):
        return (int(length) + self.page_size - 1) // self.page_size

    def _ensure_pages(self, slot, new_len):
        need = self._pages_needed(new_len)
        # count actual allocations (chunk headroom can exceed
        # pages_needed(length); recomputing from length would overwrite —
        # and leak — last round's headroom pages)
        have = int(np.count_nonzero(self.tables[slot]))
        if need > self.max_pages_per_seq:
            raise RuntimeError("sequence exceeds max_pages_per_seq")
        taken = []
        for i in range(have, need):
            if not self._free_pages:
                # roll back the partial allocation — a False return must
                # leave the allocator unchanged or the pages leak
                for j, pg in zip(range(have, have + len(taken)), taken):
                    self.tables[slot, j] = 0
                self._free_pages.extend(reversed(taken))
                return False
            taken.append(self._free_pages.pop())
            self.tables[slot, i] = taken[-1]
        return True

    def _preempt(self, slot):
        """Evict a running request under pool pressure: recycle its pages
        and requeue it — re-admission prefills prompt+generated prefix, so
        generation resumes exactly where it stopped (greedy decode is
        deterministic). The vLLM recompute-preemption policy."""
        req = self._active.pop(slot)
        self._free_slot(slot)
        req.slot = None
        self._queue.insert(0, req)

    def _free_slot(self, slot):
        # free every allocated table entry — chunk headroom means the slot
        # can hold pages beyond pages_needed(length) (0 is the trash page,
        # never allocated)
        self._free_pages.extend(
            int(p) for p in self.tables[slot] if p)
        self.tables[slot, :] = 0
        self.lengths[slot] = 0
        self._free_slots.append(slot)

    # ----------------------------------------------------------- jit bodies
    # Pages travel as a flat list so jit sees ordinary pytrees and donation
    # reuses the (large) page buffers in place. These helpers are PURE with
    # respect to the engine (never mutate self inside a trace).
    def _states_from(self, pages_flat, tables, lengths, prefill_valid=None):
        L = self.cfg.num_layers
        kp, vp = pages_flat[:L], pages_flat[L:2 * L]
        sc = pages_flat[2 * L:3 * L] if self.quantized else [None] * L
        return [
            PagedCacheState(kp[i], vp[i], sc[i], tables, lengths,
                            self.page_size, prefill_valid=prefill_valid)
            for i in range(L)
        ]

    @staticmethod
    def _pages_of(states):
        out = [st.k_pages for st in states] + [st.v_pages for st in states]
        if states[0].quantized:
            out += [st.scale_pages for st in states]
        return out

    def _set_pages(self, pages_flat):
        """Host-side writeback after a jitted call returns."""
        L = self.cfg.num_layers
        self.k_pages = list(pages_flat[:L])
        self.v_pages = list(pages_flat[L:2 * L])
        if self.quantized:
            self.scale_pages = list(pages_flat[2 * L:3 * L])

    def _pages_flat(self):
        out = list(self.k_pages) + list(self.v_pages)
        if self.quantized:
            out += list(self.scale_pages)
        return out

    def _get_prefill(self, bucket):
        if bucket in self._prefill_fns:
            return self._prefill_fns[bucket]
        model, engine = self.model, self

        import functools

        @functools.partial(jax.jit, donate_argnums=(1,))
        def prefill(params, pages_flat, ids, valid, tables_row, lengths_row):
            from ..jit import swapped_params

            with swapped_params(model, params), pause_tape():
                states = engine._states_from(pages_flat, tables_row,
                                             lengths_row,
                                             prefill_valid=valid)
                logits, new_states = model.forward(Tensor._wrap(ids),
                                                   caches=states)
                lg = logits._data if isinstance(logits, Tensor) else logits
                last = jnp.take_along_axis(
                    lg, (valid - 1)[:, None, None], axis=1)[:, 0]
                tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
                return tok, engine._pages_of(new_states)

        self._prefill_fns[bucket] = prefill
        return prefill

    def _get_decode(self):
        if self._decode_fn is not None:
            return self._decode_fn
        model, engine = self.model, self
        chunk = self.chunk_size

        import functools

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode_chunk(params, pages_flat, tables, lengths, last_tok):
            from ..jit import swapped_params

            with swapped_params(model, params), pause_tape():
                def body(carry, _):
                    pages_flat, lengths, last = carry
                    states = engine._states_from(pages_flat, tables, lengths)
                    logits, new_states = model.forward(
                        Tensor._wrap(last[:, None]), caches=states)
                    lg = (logits._data if isinstance(logits, Tensor)
                          else logits)
                    nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                    # idle slots keep emitting garbage; host discards
                    return ((engine._pages_of(new_states),
                             new_states[0].lengths, nxt), nxt)

                (pages_flat, lengths, _), toks = jax.lax.scan(
                    body, (pages_flat, lengths, last_tok), None, length=chunk)
            return jnp.swapaxes(toks, 0, 1), pages_flat, lengths

        self._decode_fn = decode_chunk
        return decode_chunk

    # ------------------------------------------------------------ scheduling
    @staticmethod
    def _prefix(req):
        """Tokens that must be in the cache before decode continues: the
        prompt plus anything already generated (non-empty after a
        preemption — re-prefilling the full prefix resumes generation)."""
        if req.tokens:
            return np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])
        return req.prompt

    def _admit(self):
        """Prefill queued requests into free slots (one compiled prefill per
        pow2 prompt bucket)."""
        admitted = []
        while self._queue and self._free_slots:
            req = self._queue[0]
            prefix = self._prefix(req)
            need = self._pages_needed(prefix.size + self.chunk_size)
            if need > len(self._free_pages):
                break  # pool pressure: let running requests drain first
            slot = self._free_slots.pop()
            self._queue.pop(0)
            if not self._ensure_pages(slot, prefix.size):
                self._free_slots.append(slot)
                self._queue.insert(0, req)
                break
            bucket = 1
            while bucket < prefix.size:
                bucket *= 2
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :prefix.size] = prefix
            prefill = self._get_prefill(bucket)
            tok, pages_flat = prefill(
                self._params, self._pages_flat(), jnp.asarray(ids),
                jnp.asarray([prefix.size], jnp.int32),
                jnp.asarray(self.tables[slot:slot + 1]),
                jnp.zeros((1,), jnp.int32))
            self._set_pages(pages_flat)
            self.lengths[slot] = prefix.size
            first = int(jax.device_get(tok)[0])
            req.slot = slot
            self._active[slot] = req
            self._harvest(req, [first])
            self._last_tok[slot] = first
            if req.done:  # single remaining token: finished at prefill
                del self._active[slot]
                self._free_slot(slot)
            admitted.append(req)
        return admitted

    def _harvest(self, req, toks):
        """Append generated tokens to a request, honoring eos/max."""
        fresh = []
        for t in toks:
            if req.done or len(req.tokens) >= req.max_new_tokens:
                req.done = True
                break
            req.tokens.append(int(t))
            fresh.append(int(t))
            if self.eos_id is not None and t == self.eos_id:
                req.done = True
            elif len(req.tokens) >= req.max_new_tokens:
                req.done = True
        if fresh and req.on_token is not None:
            req.on_token(fresh)

    def step(self) -> int:
        """One scheduling iteration: admit, decode one chunk, harvest.
        Returns the number of live requests remaining (queued + active)."""
        self._admit()
        if self._active:
            # top up pages for the coming chunk; pool pressure preempts
            # (recompute policy) — never a hard crash, and add_request
            # guarantees any single request fits the pool alone
            for slot in sorted(self._active,
                               key=lambda s: -int(self.lengths[s])):
                if len(self._active) == 1:
                    break  # last one always fits (admission invariant)
                if not self._ensure_pages(
                        slot, int(self.lengths[slot]) + self.chunk_size):
                    self._preempt(slot)
            for slot in list(self._active):
                if not self._ensure_pages(
                        slot, int(self.lengths[slot]) + self.chunk_size):
                    raise RuntimeError(
                        "KV page pool exhausted even after preemption; "
                        "the add_request capacity check should prevent this")
            decode = self._get_decode()
            toks, pages_flat, lengths = decode(
                self._params, self._pages_flat(),
                jnp.asarray(self.tables), jnp.asarray(self.lengths),
                jnp.asarray(self._last_tok))
            self._set_pages(pages_flat)
            toks = np.asarray(jax.device_get(toks))  # [slots, chunk]
            self.lengths = np.asarray(jax.device_get(lengths)).copy()
            for slot, req in list(self._active.items()):
                self._harvest(req, toks[slot])
                self._last_tok[slot] = toks[slot, -1]
                if req.done:
                    del self._active[slot]
                    self._free_slot(slot)
        elif self._queue:
            raise RuntimeError(
                "scheduler stalled: queued requests but nothing active and "
                "no admission possible (page pool too fragmented/small)")
        return len(self._queue) + len(self._active)

    def run(self, requests=None) -> List[Request]:
        """Serve ``requests`` (or whatever is queued) to completion."""
        if requests:
            done = list(requests)
        else:
            done = list(self._queue)
        while self.step():
            pass
        return done


def bench_engine_decode(cfg, on_tpu):
    """Driver-visible paged-serving benchmark: mixed-length requests through
    the Engine, steady-state decode throughput (bf16 weights + paged cache;
    plus the int8-cache variant)."""
    from ..models.gpt import GPTForCausalLM

    model = GPTForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    rng = np.random.default_rng(3)
    out = {}
    for quant, key in ((False, "paged"), (True, "paged_int8")):
        slots = 8 if on_tpu else 2
        new_tokens = 192 if on_tpu else 8
        eng = Engine(model, max_slots=slots,
                     num_pages=(slots + 2) * cfg.max_position // 16 + 1,
                     page_size=16, chunk_size=32 if on_tpu else 4,
                     quantized_cache=quant)
        prompts = [rng.integers(0, cfg.vocab_size,
                                (int(rng.integers(24, 120)),))
                   for _ in range(slots)]
        for p in prompts:
            eng.add_request(p, new_tokens)
        reqs = list(eng._queue)
        eng._admit()       # prefill (compiles) outside the timed window
        eng.step()         # decode-chunk compile + first chunk outside too
        done0 = sum(len(r.tokens) for r in reqs)
        t0 = time.perf_counter()
        while eng.step():
            pass
        dt = time.perf_counter() - t0
        total = sum(len(r.tokens) for r in reqs) - done0
        out[f"{key}_decode_tokens_per_sec"] = round(total / dt, 1)
    return out
