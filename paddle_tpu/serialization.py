"""paddle.save / paddle.load parity (reference: python/paddle/framework/io.py —
pickle protocol with per-tensor numpy buffers).

Distributed sharded/async checkpointing lives in
paddle_tpu.distributed.checkpoint (orbax/tensorstore-backed); this module is
the single-process façade both share.
"""
from __future__ import annotations

import os
import pickle
import uuid
from typing import Any

import numpy as np

from .framework.tensor import Tensor

__all__ = ["save", "load"]


class _TensorPickle:
    """Placeholder written into the pickle stream for each Tensor."""

    def __init__(self, array: np.ndarray):
        self.array = array


def _pack(obj):
    if isinstance(obj, Tensor):
        arr = np.asarray(obj.numpy())
        # bfloat16 has no native numpy dtype outside ml_dtypes; keep it
        return _TensorPickle(arr)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        packed = [_pack(v) for v in obj]
        try:
            return t(packed)
        except TypeError:  # namedtuple
            return t(*packed)
    return obj


def _unpack(obj, return_tensor=True):
    if isinstance(obj, _TensorPickle):
        return Tensor(obj.array) if return_tensor else obj.array
    if isinstance(obj, dict):
        return {k: _unpack(v, return_tensor) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        unpacked = [_unpack(v, return_tensor) for v in obj]
        try:
            return t(unpacked)
        except TypeError:
            return t(*unpacked)
    return obj


def save(obj: Any, path: str, protocol: int = 4):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # atomic single-file save (ISSUE 7 satellite): stage into a sibling
    # tmp file, fsync, then os.replace — a crash mid-write leaves either
    # the old file or the new one, never a torn pickle.
    tmp = f"{path}.tmp-{uuid.uuid4().hex[:8]}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(_pack(obj), f, protocol=protocol)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load(path: str, return_numpy: bool = False):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_tensor=not return_numpy)
