"""Profiler facade (reference: python/paddle/profiler/profiler.py over the
C++ host/CUPTI tracers — SURVEY.md §5.1).

TPU-native: ``jax.profiler`` (XProf) is the device tracer; host annotations
via ``jax.profiler.TraceAnnotation``. The reference's scheduler
(wait/warmup/active windows keyed by step) and summary UX are preserved;
the trace itself is an XProf artifact viewable in tensorboard.
"""
from __future__ import annotations

import contextlib
import os
import time
from enum import Enum
from typing import Callable, Optional

import jax

__all__ = [
    "Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "mfu",
]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Step-keyed state machine (reference: paddle.profiler.make_scheduler)."""
    period = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready callback: the XProf trace directory is the artifact."""

    def handler(prof: "Profiler"):
        prof._last_export = dir_name

    handler._dir = dir_name
    return handler


class RecordEvent:
    """Host-span annotation (reference: paddle.profiler.RecordEvent →
    here jax.profiler.TraceAnnotation so spans appear in XProf)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False):
        if isinstance(scheduler, tuple):
            lo, hi = scheduler
            scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo, repeat=1)
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._tracing = False
        self._dir = getattr(on_trace_ready, "_dir", None) or os.path.join(
            os.getcwd(), "profiler_log"
        )
        self._last_export = None
        self._step_times = []
        self._t_last = None

    # --------------------------------------------------------------- control
    def start(self):
        self._t_last = time.perf_counter()
        self._transition()

    def stop(self):
        # the final in-flight step (started by the last step()/start())
        # used to be dropped — its time belongs in the summary
        if self._t_last is not None:
            self._step_times.append(time.perf_counter() - self._t_last)
            self._t_last = None
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def step(self):
        now = time.perf_counter()
        if self._t_last is not None:
            self._step_times.append(now - self._t_last)
        self._t_last = now
        self._step += 1
        self._transition()

    def _transition(self):
        state = (self._scheduler(self._step) if self._scheduler
                 else ProfilerState.RECORD)
        if self._timer_only:
            self._state = state
            return
        should_trace = state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if should_trace and not self._tracing:
            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
            self._tracing = True
        elif not should_trace and self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self._on_trace_ready:
                self._on_trace_ready(self)
        self._state = state

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # --------------------------------------------------------------- summary
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np

        ts = np.asarray(self._step_times) * 1e3
        steps_per_sec = 1e3 * len(ts) / ts.sum() if ts.sum() > 0 else 0.0
        lines = [
            "---- step time summary ----",
            f"steps: {len(ts)}   mean: {ts.mean():.2f} ms   p50: {np.percentile(ts, 50):.2f} ms"
            f"   p90: {np.percentile(ts, 90):.2f} ms   p99: {np.percentile(ts, 99):.2f} ms"
            f"   max: {ts.max():.2f} ms",
            f"steps/sec: {steps_per_sec:.2f}",
        ]
        if self._last_export:
            lines.append(f"trace exported to: {self._last_export}")
        return "\n".join(lines)


def mfu(n_params: int, tokens_per_sec_per_chip: float,
        peak_flops_per_chip: Optional[float] = None,
        flops_per_token: Optional[float] = None) -> float:
    """North-star runtime readout (BASELINE.md convention: 6N model FLOPs,
    remat excluded, per-chip over per-chip)."""
    if peak_flops_per_chip is None:
        kind = getattr(jax.devices()[0], "device_kind", "")
        table = {"TPU v6": 918e12, "TPU v5p": 459e12, "TPU v5 lite": 197e12,
                 "TPU v5e": 197e12, "TPU v4": 275e12}
        peak_flops_per_chip = next(
            (v for k, v in table.items() if kind.startswith(k)), 197e12
        )
    fpt = flops_per_token if flops_per_token is not None else 6.0 * n_params
    return tokens_per_sec_per_chip * fpt / peak_flops_per_chip
