"""Trip-count-aware matmul-FLOPs estimator over jaxprs.

XLA's ``compiled.cost_analysis()`` counts each HLO op once — a ``lax.scan``
body's FLOPs are not multiplied by the trip count and conditional branches
are accounted inconsistently, so it cannot compare differently-structured
schedules (e.g. remat-1F1B's switch-heavy tick versus AD-through-scan).
This walker traces a function, then recursively sums ``dot_general`` FLOPs:

* ``scan``/``while``: body count x trip count (while loops without a static
  bound count their body once and set ``unbounded_while`` in the report);
* ``cond``/``switch``: the MAX over branches (one branch executes per hit);
* ``pjit``/``custom_vjp``/``custom_jvp``/``remat``/``shard_map``/closed
  calls: recurse — so rematerialized forwards inside a backward are
  *counted*, which is exactly what schedule-efficiency comparisons need
  (reference capability: the profiler flop accounting of
  paddle.profiler / host_statistic_flops).

Estimates are per executing device for shard_map programs (the SPMD
program body is walked once).
"""
from __future__ import annotations

import math

import jax
import numpy as np

__all__ = ["dot_flops_of", "count_jaxpr_dot_flops"]


def _dot_eqn_flops(eqn) -> float:
    dnums = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dnums
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    batch = math.prod(a.shape[i] for i in lb) if lb else 1
    k = math.prod(a.shape[i] for i in lc) if lc else 1
    m = math.prod(a.shape[i] for i in range(a.ndim)
                  if i not in set(lc) | set(lb))
    n = math.prod(b.shape[i] for i in range(b.ndim)
                  if i not in set(rc) | set(rb))
    return 2.0 * batch * m * n * k


def _walk(jaxpr, report) -> float:
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_eqn_flops(eqn)
        elif name == "scan":
            body = eqn.params["jaxpr"].jaxpr
            total += eqn.params["length"] * _walk(body, report)
        elif name == "while":
            body = eqn.params["body_jaxpr"].jaxpr
            report["unbounded_while"] = True
            total += _walk(body, report)
        elif name == "cond":
            branches = eqn.params["branches"]
            total += max((_walk(br.jaxpr, report) for br in branches),
                         default=0.0)
        else:
            # recurse into any sub-jaxpr-carrying primitive (pjit, remat,
            # custom_vjp_call, shard_map, closed_call, ...)
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                sub = eqn.params.get(key) if eqn.params else None
                if sub is not None:
                    inner = getattr(sub, "jaxpr", sub)
                    total += _walk(inner, report)
                    break
    return total


def count_jaxpr_dot_flops(jaxpr):
    """Sum dot_general FLOPs of a (closed) jaxpr with loop trip counts
    applied. Returns ``(flops, report)``."""
    report = {"unbounded_while": False}
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    return _walk(inner, report), report


def dot_flops_of(fn, *args, **kwargs):
    """Trace ``fn(*args, **kwargs)`` and return its estimated matmul FLOPs
    (trip-count-aware; see module docstring)."""
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    flops, _ = count_jaxpr_dot_flops(jaxpr)
    return flops
