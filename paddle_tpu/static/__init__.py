"""paddle.static surface (reference: python/paddle/static/).

TPU-native stance (SURVEY.md §3.4): "static mode" is explicit capture —
but a REAL capture, not a placeholder. With static mode enabled, every
``apply_op`` call records ``(fn, inputs, outputs)`` into the current
``Program`` (the ProgramDesc analogue: an op list over named variables).
``Executor.run`` replays the recorded op list as ONE jitted function of the
feeds — XLA is the InterpreterCore
(paddle/fluid/framework/new_executor/interpretercore.cc): dependency
ordering, stream assignment and buffer liveness all come from the compiler,
not a hand-written scheduler.

Classic reference UX works end-to-end:

    paddle.enable_static()
    x = paddle.static.data("x", [None, 8])
    y = my_net(x)                       # ops recorded into main_program
    exe = paddle.static.Executor()
    out, = exe.run(feed={"x": arr}, fetch_list=[y])

Divergence from the reference, by design: parameter initialization is EAGER
(it happens when the Layer is constructed), so startup programs are
accepted for API compatibility but always empty — there are no init ops to
collect, and ``exe.run(startup)`` is a documented no-op.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..jit import InputSpec  # noqa: F401

__all__ = ["InputSpec", "Program", "Executor", "data", "program_guard",
           "default_main_program", "default_startup_program"]

_static_mode = False


def _enable():
    global _static_mode
    _static_mode = True
    from ..framework import tensor as _tensor

    _tensor._STATIC_CAPTURE = True


def _disable():
    global _static_mode
    _static_mode = False
    from ..framework import tensor as _tensor

    _tensor._STATIC_CAPTURE = False


def _enabled():
    return _static_mode


class Program:
    """Recorded op list over variables (the ProgramDesc analogue).

    ``ops``: list of (fn, input_tensors, kwargs, output_tensors); variables
    are identified by Tensor object identity, feeds by ``data()`` name."""

    def __init__(self, fn=None):
        self._fn = fn  # legacy captured-callable mode (jit.to_static path)
        self.ops: List[tuple] = []
        self.feeds: Dict[str, object] = {}  # name -> placeholder Tensor

    def _record(self, fn, inputs, kwargs, outputs):
        self.ops.append((fn, tuple(inputs), dict(kwargs), tuple(outputs)))

    def clone(self, for_test=False):
        p = Program(self._fn)
        p.ops = list(self.ops)
        p.feeds = dict(self.feeds)
        return p

    def is_empty(self):
        return not self.ops and self._fn is None


_default_main = Program()
_default_startup = Program()
_guard_stack: List[Program] = []


def default_main_program() -> Program:
    return _guard_stack[-1] if _guard_stack else _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    """Route recording into ``main`` (reference: static.program_guard).
    ``startup`` is accepted for API parity but stays empty: parameter
    initialization is eager at Layer construction (see module docstring)."""

    def __init__(self, main: Program, startup: Optional[Program] = None):
        self.main = main
        self.startup = startup

    def __enter__(self):
        _guard_stack.append(self.main)
        return self.main

    def __exit__(self, *exc):
        _guard_stack.pop()
        return False


def _maybe_record(fn, inputs, kwargs, outputs):
    """Called by framework.tensor.apply_op when static mode is on."""
    if _static_mode:
        default_main_program()._record(fn, inputs, kwargs, outputs)


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed variable: a named placeholder Tensor recorded in the
    current program (None dims become 1 for the capture trace; Executor.run
    replays with the real fed shapes)."""
    import jax.numpy as jnp

    from ..framework.tensor import Tensor

    cap_shape = tuple(1 if (s is None or s < 0) else int(s) for s in shape)
    t = Tensor(jnp.zeros(cap_shape, dtype))
    t.name = name
    default_main_program().feeds[name] = t
    return t


class Executor:
    """Replays a Program as one jitted function of the feeds AND the current
    parameter values (reference: python/paddle/base/executor.py; execution
    engine = XLA). Parameters are runtime inputs, not trace-time constants:
    updating weights (training, ``set_state_dict``) between runs is
    reflected without retracing."""

    def __init__(self, place=None):
        self.place = place
        # values hold strong refs to (program, fetch_list, params) so the
        # id-based key can never alias a recycled object
        self._compiled: Dict[tuple, tuple] = {}

    @staticmethod
    def _param_tensors(program: Program):
        """Distinct non-placeholder Tensor inputs across the program's ops,
        in first-use order — the replay's runtime parameter slots."""
        feed_ids = {id(t) for t in program.feeds.values()}
        produced = {id(o) for _, _, _, outs in program.ops for o in outs}
        seen, params = set(), []
        for _, inputs, _, _ in program.ops:
            for t in inputs:
                if (hasattr(t, "_data") and id(t) not in feed_ids
                        and id(t) not in produced and id(t) not in seen):
                    seen.add(id(t))
                    params.append(t)
        return params

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy=True, **kwargs):
        import jax

        program = program if program is not None else default_main_program()
        feed = feed or {}
        if program._fn is not None:  # legacy captured-callable programs
            out = program._fn(**feed)
            out = out if isinstance(out, (list, tuple)) else [out]
            return [jax.device_get(getattr(o, "_data", o)) for o in out]
        if not program.ops:
            return []
        fetch_list = fetch_list or []

        missing = sorted(set(program.feeds) - set(feed))
        if missing:
            raise KeyError(
                f"Executor.run: feed is missing declared variables {missing}"
            )
        feed_names = tuple(sorted(feed))
        feed_arrays = [jax.numpy.asarray(feed[k]) for k in feed_names]
        key = (id(program), len(program.ops), feed_names,
               tuple(a.shape for a in feed_arrays),
               tuple(id(f) for f in fetch_list))
        entry = self._compiled.get(key)
        if entry is None:
            params = self._param_tensors(program)
            run_fn = jax.jit(self._make_replay(program, feed_names,
                                               fetch_list, params))
            entry = (program, tuple(fetch_list), params, run_fn)
            self._compiled[key] = entry
        _, _, params, run_fn = entry
        outs = run_fn(feed_arrays, [p._data for p in params])
        if return_numpy:
            import numpy as np

            return [np.asarray(jax.device_get(o)) for o in outs]
        return list(outs)

    @staticmethod
    def _make_replay(program: Program, feed_names, fetch_list, params):
        def replay(feed_arrays, param_arrays):
            env = {}
            for name, arr in zip(feed_names, feed_arrays):
                ph = program.feeds.get(name)
                if ph is not None:
                    env[id(ph)] = arr
            for t, arr in zip(params, param_arrays):
                env[id(t)] = arr

            def val(t):
                if id(t) in env:
                    return env[id(t)]
                return getattr(t, "_data", t)

            for fn, inputs, kw, outputs in program.ops:
                outs = fn(*[val(i) for i in inputs], **kw)
                outs = outs if isinstance(outs, (tuple, list)) else (outs,)
                for o_t, o in zip(outputs, outs):
                    env[id(o_t)] = o
            return [val(f) for f in fetch_list]

        return replay
