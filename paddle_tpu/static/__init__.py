"""paddle.static surface (reference: python/paddle/static/).

TPU-native stance (SURVEY.md §3.4): "static mode" is explicit jit capture —
there is no global Program being mutated under the user. ``enable_static()``
flips a flag consumed by dual-mode call sites; the real compiled path is
``paddle_tpu.jit.to_static`` / ``jax.jit``. The Executor here runs captured
programs (callables) rather than interpreting an op list — InterpreterCore's
job (paddle/fluid/framework/new_executor/interpretercore.cc) belongs to XLA.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401

_static_mode = False


def _enable():
    global _static_mode
    _static_mode = True


def _disable():
    global _static_mode
    _static_mode = False


def _enabled():
    return _static_mode


class Program:
    """Placeholder program object for API parity; holds a captured callable."""

    def __init__(self, fn=None):
        self._fn = fn

    def clone(self, for_test=False):
        return Program(self._fn)


def default_main_program():
    return Program()


def default_startup_program():
    return Program()


class Executor:
    """Runs captured callables (reference: python/paddle/base/executor.py —
    but execution is jax.jit, so 'run' is a function call)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if program is None or program._fn is None:
            return []
        import jax

        out = program._fn(**(feed or {}))
        out = out if isinstance(out, (list, tuple)) else [out]
        return [jax.device_get(getattr(o, "_data", o)) for o in out]


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)
