"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py).

Host pipeline: sample indices → worker pool assembles numpy batches →
bounded prefetch queue → ``jax.device_put`` double-buffering.

Workers are **spawned processes** by default (the reference's
worker-process design: dataloader_iter.py _DataLoaderIterMultiProcess) with
dynamic task dispatch over duplex pipes: the parent streams
``(batch_index, sample_indices)`` tasks and each worker returns batches as
they finish, so a slow batch doesn't stall a statically-assigned shard.
Batch payloads travel one of two ways:

* ``use_shared_memory=True`` (default, reference parity): array leaves are
  written into a ``multiprocessing.shared_memory`` segment and only the
  (name, shapes, dtypes, offsets) metadata rides the pipe; the parent copies
  out and acks so the worker can unlink. This is the reference's shared-mem
  queue design (``use_shared_memory`` in dataloader_iter.py) — large batches
  skip pickle framing and the 64 KiB socketpair chunking entirely.
* otherwise pickled frames over the OS pipe.

``persistent_workers=True`` keeps the pool alive across epochs (dataset is
shipped to each worker once at spawn, not re-pickled per epoch). ``spawn``
(never fork — fork is hostile to a live PJRT client) and children are pinned
to the CPU backend so they can't claim the TPU chip. Thread workers remain
as the automatic fallback when the dataset/collate_fn can't pickle (and via
``worker_type="thread"``): their numpy/PIL work releases the GIL, but
pure-Python transforms serialize — the process pool is what scales those
(round-1 verdict #8).
"""
from __future__ import annotations

import multiprocessing
import os
import pickle
import queue
import threading
import warnings
from typing import Callable, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

# below this many payload bytes the pipe wins (shm create/attach has fixed
# syscall cost); above it the shared segment skips pickle + pipe chunking
_SHM_MIN_BYTES = 1 << 16


class _NullSink:
    """Write-discarding file object for the picklability probe: streams the
    pickle instead of materializing the whole serialized dataset in memory
    (advisor r2: probing with pickle.dumps spiked memory for big in-memory
    datasets)."""

    def write(self, b):
        return len(b)


def _probe_picklable(*objs) -> bool:
    try:
        pickle.dump(objs, _NullSink(), protocol=pickle.HIGHEST_PROTOCOL)
        return True
    except Exception:
        return False


# ------------------------------------------------------- batch tree helpers


def _tree_flatten(obj):
    """Split a collated batch into (array_leaves, structure). Local —
    workers must not import jax just for tree_util."""
    arrs = []

    def rec(o):
        if isinstance(o, np.ndarray):
            arrs.append(o)
            return ("a", len(arrs) - 1)
        if isinstance(o, tuple):
            return ("t", [rec(x) for x in o])
        if isinstance(o, list):
            return ("l", [rec(x) for x in o])
        if isinstance(o, dict):
            return ("d", {k: rec(v) for k, v in o.items()})
        return ("v", o)

    return arrs, rec(obj)


def _tree_unflatten(tree, arrs):
    tag, val = tree
    if tag == "a":
        return arrs[val]
    if tag == "t":
        return tuple(_tree_unflatten(x, arrs) for x in val)
    if tag == "l":
        return [_tree_unflatten(x, arrs) for x in val]
    if tag == "d":
        return {k: _tree_unflatten(v, arrs) for k, v in val.items()}
    return val


# ----------------------------------------------------------- worker process


def _unlink_segment(name):
    """Best-effort unlink of a shared-memory segment a dead worker can no
    longer reclaim (the attach/close pair balances the resource_tracker
    registration the attach performs)."""
    from multiprocessing import shared_memory

    try:
        seg = shared_memory.SharedMemory(name=name)
        seg.unlink()
        seg.close()
    except FileNotFoundError:
        pass
    except Exception:
        pass


def _process_worker(conn, dataset, collate_fn, worker_init_fn, wid, use_shm):
    """Child entry: serve ("task", i, idxs) requests until ("stop",).

    Results go back as ("data", i, batch) pickle frames, or — when shm is on
    and the batch is big enough — as ("shm", i, name, metas, tree) with the
    arrays in a shared segment the worker unlinks on the parent's ack."""
    from multiprocessing import shared_memory

    pending = {}
    try:
        if worker_init_fn is not None:
            worker_init_fn(wid)
        while True:
            msg = conn.recv()
            kind = msg[0]
            if kind == "stop":
                break
            if kind == "ack":
                shm = pending.pop(msg[1], None)
                if shm is not None:
                    shm.close()
                    shm.unlink()
                continue
            _, epoch, i, idxs = msg
            try:
                data = collate_fn([dataset[j] for j in idxs])
                sent = False
                if use_shm:
                    arrs, tree = _tree_flatten(data)
                    nbytes = sum(a.nbytes for a in arrs)
                    if arrs and nbytes >= _SHM_MIN_BYTES:
                        shm = shared_memory.SharedMemory(
                            create=True, size=nbytes)
                        metas, off = [], 0
                        for a in arrs:
                            a = np.ascontiguousarray(a)
                            np.ndarray(a.shape, a.dtype, buffer=shm.buf,
                                       offset=off)[...] = a
                            metas.append((a.shape, a.dtype.str, off))
                            off += a.nbytes
                        pending[shm.name] = shm
                        conn.send(("shm", epoch, i, shm.name, metas, tree))
                        sent = True
                if not sent:
                    conn.send(("data", epoch, i, data))
            except Exception as e:  # surfaced in the consumer
                try:
                    conn.send(("err", epoch, i, e))
                except Exception:
                    # unpicklable exception: ship a picklable stand-in
                    # rather than dying with the task marked in-flight
                    conn.send(("err", epoch, i,
                               RuntimeError(f"worker {wid} batch {i}: "
                                            f"{type(e).__name__}: {e}")))
    except (EOFError, OSError):
        pass  # parent went away — clean exit
    finally:
        for shm in pending.values():
            try:
                shm.close()
                shm.unlink()
            except Exception:
                pass
        try:
            conn.close()
        except Exception:
            pass


class _ProcessPool:
    """Spawned worker pool with dynamic dispatch and ordered delivery.

    All pipe *sends* happen on the consumer thread (tasks, acks, stop); one
    puller thread per worker does the *recvs* — duplex Connections allow
    concurrent send/recv, they just can't share a direction across threads.
    """

    def __init__(self, dataset, collate_fn, worker_init_fn, num_workers,
                 use_shm):
        ctx = multiprocessing.get_context("spawn")
        # children must never claim the TPU chip or init a TPU backend;
        # env is captured at spawn time, so pin and restore around start()
        saved = {k: os.environ.get(k)
                 for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        self.procs, self.conns = [], []
        self.use_shm = use_shm
        self.closed = False
        try:
            for w in range(num_workers):
                parent_conn, child_conn = ctx.Pipe(duplex=True)
                p = ctx.Process(
                    target=_process_worker,
                    args=(child_conn, dataset, collate_fn, worker_init_fn,
                          w, use_shm),
                    daemon=True)
                p.start()
                child_conn.close()
                self.procs.append(p)
                self.conns.append(parent_conn)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        # ONE puller per worker for the pool's lifetime (a persistent pool
        # must not stack a second recv-er on the same Connection next epoch)
        self.out_q: "queue.Queue" = queue.Queue()
        self._DEAD = DEAD = object()
        self._dead = set()
        self._epoch = 0  # results are epoch-tagged: an abandoned epoch's
        # in-flight results must not be mistaken for the next epoch's

        def pull(wid, conn, out_q=self.out_q):
            try:
                while True:
                    out_q.put((wid, conn.recv()))
            except (EOFError, OSError):
                out_q.put((wid, DEAD))

        self._pullers = [
            threading.Thread(target=pull, args=(w, c), daemon=True)
            for w, c in enumerate(self.conns)
        ]
        for t in self._pullers:
            t.start()

    def _send(self, wid, msg) -> bool:
        """Send to a worker; a broken pipe marks it dead instead of raising
        into the training loop (its DEAD sentinel may still be in flight)."""
        if wid in self._dead:
            return False
        try:
            self.conns[wid].send(msg)
            return True
        except (OSError, ValueError):
            self._dead.add(wid)
            return False

    def run_epoch(self, batches, prefetch_per_worker, timeout=0):
        """Yield collated batches for ``batches`` (list of index lists) in
        order. Tasks are dispatched ``prefetch_per_worker`` deep per worker;
        a worker gets its next task the moment a result lands, and a dead
        worker's in-flight tasks are redispatched to the survivors."""
        from collections import deque
        from multiprocessing import shared_memory

        n = len(batches)
        W = len(self.conns)
        out_q = self.out_q
        DEAD = self._DEAD
        self._epoch += 1
        epoch = self._epoch
        next_task = 0
        redo: "deque" = deque()  # batch indices orphaned by a dead worker
        inflight = {w: set() for w in range(W)}

        def feed(wid):
            nonlocal next_task
            while True:
                if redo:
                    i = redo.popleft()
                elif next_task < n:
                    i = next_task
                    next_task += 1
                else:
                    return False
                if self._send(wid, ("task", epoch, i, batches[i])):
                    inflight[wid].add(i)
                    return True
                # send failed: worker just died — requeue and give up on it
                redo.appendleft(i)
                reap(wid)
                return False

        def reap(wid):
            """Mark dead + orphan its in-flight tasks for redispatch."""
            self._dead.add(wid)
            redo.extend(sorted(inflight.pop(wid, ())))

        # prime each live worker prefetch-deep
        for w in range(W):
            if w in self._dead:
                continue
            for _ in range(prefetch_per_worker):
                if not feed(w):
                    break

        results, want = {}, 0
        while want < n:
            while want not in results:
                if len(self._dead) == W and out_q.empty():
                    # every worker is gone (their pullers have exited, so
                    # the queue is final) — the wanted batch can't arrive
                    raise RuntimeError(
                        "DataLoader worker processes exited before "
                        "delivering all batches")
                # orphaned work + live workers with a free slot → redispatch
                while redo:
                    target = next(
                        (w for w in range(W) if w not in self._dead
                         and len(inflight[w]) < prefetch_per_worker), None)
                    if target is None or not feed(target):
                        break
                try:
                    wid, msg = out_q.get(
                        timeout=timeout if timeout > 0 else None)
                except queue.Empty:
                    raise RuntimeError(
                        f"DataLoader timed out after {timeout}s waiting "
                        "for a worker batch")
                if msg is DEAD:
                    if wid not in self._dead or inflight.get(wid):
                        reap(wid)
                    continue
                kind = msg[0]
                if kind == "shm":
                    _, ep, i, name, metas, tree = msg
                    if ep != epoch:
                        # stale result from an abandoned epoch: ack so the
                        # worker unlinks the segment, drop the payload —
                        # and if the worker is already gone, the unlink
                        # falls to us (ADVICE r3: a dead worker's
                        # published segment otherwise leaks /dev/shm)
                        if not self._send(wid, ("ack", name)):
                            _unlink_segment(name)
                        continue
                    # NOTE: attach re-registers the name with the (shared,
                    # spawn-inherited) resource_tracker, whose cache is a
                    # set — the worker's unlink after our ack is the single
                    # balancing unregister; do NOT unregister here too
                    seg = shared_memory.SharedMemory(name=name)
                    try:
                        arrs = [
                            np.array(np.ndarray(
                                shape, np.dtype(dt), buffer=seg.buf,
                                offset=off))
                            for shape, dt, off in metas
                        ]
                        if not self._send(wid, ("ack", name)):
                            # worker died after publishing: it can never
                            # unlink — we own the segment's lifetime now
                            seg.unlink()
                    finally:
                        seg.close()
                    results[i] = _tree_unflatten(tree, arrs)
                else:
                    _, ep, i, payload = msg
                    if ep != epoch:
                        continue
                    if kind == "err":
                        raise payload
                    results[i] = payload
                inflight.get(wid, set()).discard(i)
                feed(wid)
            yield results.pop(want)
            want += 1

    def alive(self) -> bool:
        return (not self.closed and not self._dead
                and all(p.is_alive() for p in self.procs))

    def close(self):
        if self.closed:
            return
        self.closed = True
        for c in self.conns:
            try:
                c.send(("stop",))
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
                p.join(timeout=2)
        # The puller threads OWN the connections (recv is not thread-safe
        # to share); once the workers are gone their ends close, the
        # pullers hit EOF, enqueue DEAD and exit — wait for that, then
        # drain out_q for undelivered shm results: a terminated worker
        # never sees the ack for segments it already published, so the
        # unlink falls to us (ADVICE r3 — otherwise each pending segment
        # leaks /dev/shm space until interpreter exit)
        for t in getattr(self, "_pullers", ()):
            t.join(timeout=2)
        try:
            while True:
                _, msg = self.out_q.get_nowait()
                if (isinstance(msg, tuple) and msg
                        and msg[0] == "shm"):
                    _unlink_segment(msg[3])
        except queue.Empty:
            pass
        for c in self.conns:
            try:
                c.close()
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def default_collate_fn(batch):
    """Stack samples into batched arrays (reference:
    python/paddle/io/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    from ..framework.tensor import Tensor

    if isinstance(sample, Tensor):
        return np.stack([t.numpy() for t in batch])
    return np.asarray(batch)


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler: Optional[BatchSampler] = None,
                 batch_size=1, shuffle=False, drop_last=False,
                 collate_fn: Optional[Callable] = None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, to_device=True,
                 worker_type: Optional[str] = None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.worker_init_fn = worker_init_fn
        self.to_device = to_device
        self.use_shared_memory = bool(use_shared_memory)
        self.persistent_workers = bool(persistent_workers)
        self.timeout = timeout
        if worker_type not in (None, "process", "thread"):
            raise ValueError(f"worker_type must be 'process'/'thread', got "
                             f"{worker_type!r}")
        # None → process workers (reference parity) with thread fallback
        # when the dataset/collate_fn can't pickle
        self.worker_type = worker_type
        self._picklable: Optional[bool] = None
        self._pool: Optional[_ProcessPool] = None
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    # ------------------------------------------------------------------ iter
    def _batches_np(self):
        """Yield collated numpy batches (worker-pool or inline)."""
        if self._iterable:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not self.drop_last:
                yield self.collate_fn(buf)
            return

        index_iter = iter(self.batch_sampler)
        if self.num_workers == 0:
            for idxs in index_iter:
                yield self.collate_fn([self.dataset[i] for i in idxs])
            return

        mode = self.worker_type
        if mode in (None, "process"):
            if self._picklable is None:  # probe once, streamed to a null
                # sink — no full serialized copy is held (advisor r2)
                self._picklable = _probe_picklable(
                    self.dataset, self.collate_fn, self.worker_init_fn)
                if not self._picklable and mode != "process":
                    warnings.warn(
                        "DataLoader: dataset/collate_fn not picklable — "
                        "falling back to thread workers", RuntimeWarning,
                        stacklevel=2)
            if not self._picklable and mode == "process":
                pickle.dumps((self.dataset, self.collate_fn,
                              self.worker_init_fn))  # re-raise the error
            if self._picklable:
                yield from self._batches_process(list(index_iter))
                return

        # thread workers: fetch batches concurrently, deliver in order
        out_q: "queue.Queue" = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        batches = list(index_iter)
        n = len(batches)
        results = {}
        lock = threading.Lock()
        next_fetch = [0]
        stop = threading.Event()

        def worker(wid):
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while not stop.is_set():
                with lock:
                    i = next_fetch[0]
                    if i >= n:
                        return
                    next_fetch[0] = i + 1
                try:
                    data = self.collate_fn([self.dataset[j] for j in batches[i]])
                    out_q.put((i, data))
                except Exception as e:  # surface in consumer
                    out_q.put((i, e))

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(self.num_workers)
        ]
        for t in threads:
            t.start()
        try:
            want = 0
            while want < n:
                while want not in results:
                    try:
                        i, data = out_q.get(
                            timeout=self.timeout if self.timeout > 0
                            else None)
                    except queue.Empty:
                        raise RuntimeError(
                            f"DataLoader timed out after {self.timeout}s "
                            "waiting for a worker batch")
                    results[i] = data
                data = results.pop(want)
                if isinstance(data, Exception):
                    raise data
                yield data
                want += 1
        finally:
            stop.set()

    def _batches_process(self, batches):
        """Process-pool epoch: dynamic dispatch + ordered delivery; the pool
        outlives the epoch when ``persistent_workers`` (dataset shipped once
        at spawn). Pool size is always num_workers — a short epoch (e.g. a
        small validation pass) leaves surplus workers idle rather than
        respawning the pool at the next full epoch."""
        W = self.num_workers
        pool = self._pool
        if pool is not None and (not pool.alive() or len(pool.conns) != W):
            pool.close()
            pool = None
        if pool is None:
            pool = _ProcessPool(self.dataset, self.collate_fn,
                                self.worker_init_fn, W,
                                self.use_shared_memory)
        self._pool = pool if self.persistent_workers else None
        try:
            yield from pool.run_epoch(batches, self.prefetch_factor,
                                      self.timeout)
        finally:
            if not self.persistent_workers:
                pool.close()

    def close(self):
        """Tear down a persistent worker pool (no-op otherwise)."""
        if self._pool is not None:
            self._pool.close()
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __iter__(self):
        from ..framework.tensor import Tensor
        import jax

        def to_tensors(batch):
            if isinstance(batch, (tuple, list)):
                return [to_tensors(b) for b in batch]
            if isinstance(batch, dict):
                return {k: to_tensors(v) for k, v in batch.items()}
            if self.to_device:
                return Tensor._wrap(jax.device_put(batch))
            return Tensor._wrap(batch)

        # double buffer: device transfer of batch i+1 overlaps consumption of i
        prev = None
        for np_batch in self._batches_np():
            cur = to_tensors(np_batch)
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev
