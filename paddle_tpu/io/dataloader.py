"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py).

Host pipeline: sample indices → worker pool assembles numpy batches →
bounded prefetch queue → ``jax.device_put`` double-buffering.

Workers are **spawned processes** by default (the reference's
worker-process design: dataloader_iter.py _DataLoaderIterMultiProcess),
sending length-prefixed pickled batch frames over OS pipes (socketpair
transport) that per-worker puller threads drain into the bounded prefetch
queue. ``spawn`` (never fork — fork is hostile to a live PJRT client) and
children are pinned to the CPU backend so they can't claim the TPU chip.
Thread workers remain as the automatic fallback when the dataset/collate_fn
can't pickle (and via ``worker_type="thread"``): their numpy/PIL work
releases the GIL, but pure-Python transforms serialize — the process pool
is what scales those (round-1 verdict #8).
"""
from __future__ import annotations

import multiprocessing
import os
import pickle
import queue
import threading
import warnings
from typing import Callable, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def _process_worker(conn, dataset, collate_fn, worker_init_fn, wid,
                    assigned):
    """Child entry: compute assigned (global_index, sample_indices) batches
    in order, ship length-prefixed pickle frames over the pipe."""
    try:
        if worker_init_fn is not None:
            worker_init_fn(wid)
        for i, idxs in assigned:
            data = collate_fn([dataset[j] for j in idxs])
            conn.send_bytes(
                pickle.dumps((i, data), protocol=pickle.HIGHEST_PROTOCOL))
        conn.send_bytes(pickle.dumps((None, None)))
    except Exception as e:  # surfaced in the consumer
        try:
            conn.send_bytes(pickle.dumps((-1, e)))
        except Exception:
            pass
    finally:
        conn.close()


def default_collate_fn(batch):
    """Stack samples into batched arrays (reference:
    python/paddle/io/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    from ..framework.tensor import Tensor

    if isinstance(sample, Tensor):
        return np.stack([t.numpy() for t in batch])
    return np.asarray(batch)


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler: Optional[BatchSampler] = None,
                 batch_size=1, shuffle=False, drop_last=False,
                 collate_fn: Optional[Callable] = None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, to_device=True,
                 worker_type: Optional[str] = None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.worker_init_fn = worker_init_fn
        self.to_device = to_device
        if worker_type not in (None, "process", "thread"):
            raise ValueError(f"worker_type must be 'process'/'thread', got "
                             f"{worker_type!r}")
        # None → process workers (reference parity) with thread fallback
        # when the dataset/collate_fn can't pickle
        self.worker_type = worker_type
        self._picklable: Optional[bool] = None
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    # ------------------------------------------------------------------ iter
    def _batches_np(self):
        """Yield collated numpy batches (worker-pool or inline)."""
        if self._iterable:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not self.drop_last:
                yield self.collate_fn(buf)
            return

        index_iter = iter(self.batch_sampler)
        if self.num_workers == 0:
            for idxs in index_iter:
                yield self.collate_fn([self.dataset[i] for i in idxs])
            return

        mode = self.worker_type
        if mode in (None, "process"):
            if self._picklable is None:  # probe once, not per epoch — the
                # dump serializes the whole dataset just to be thrown away
                try:
                    pickle.dumps((self.dataset, self.collate_fn,
                                  self.worker_init_fn))
                    self._picklable = True
                except Exception:
                    self._picklable = False
                    if mode != "process":
                        warnings.warn(
                            "DataLoader: dataset/collate_fn not picklable — "
                            "falling back to thread workers", RuntimeWarning,
                            stacklevel=2)
            if not self._picklable and mode == "process":
                pickle.dumps((self.dataset, self.collate_fn,
                              self.worker_init_fn))  # re-raise the error
            if self._picklable:
                yield from self._batches_process(list(index_iter))
                return

        # thread workers: fetch batches concurrently, deliver in order
        out_q: "queue.Queue" = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        batches = list(index_iter)
        n = len(batches)
        results = {}
        lock = threading.Lock()
        next_fetch = [0]
        stop = threading.Event()

        def worker(wid):
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while not stop.is_set():
                with lock:
                    i = next_fetch[0]
                    if i >= n:
                        return
                    next_fetch[0] = i + 1
                try:
                    data = self.collate_fn([self.dataset[j] for j in batches[i]])
                    out_q.put((i, data))
                except Exception as e:  # surface in consumer
                    out_q.put((i, e))

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(self.num_workers)
        ]
        for t in threads:
            t.start()
        try:
            want = 0
            while want < n:
                while want not in results:
                    i, data = out_q.get()
                    results[i] = data
                data = results.pop(want)
                if isinstance(data, Exception):
                    raise data
                yield data
                want += 1
        finally:
            stop.set()

    def _batches_process(self, batches):
        """Spawned worker processes, round-robin batch assignment, ordered
        delivery. Frames ride OS pipes; per-worker puller threads (pipe reads
        release the GIL) feed a bounded queue sized num_workers ×
        prefetch_factor for lookahead."""
        n = len(batches)
        W = min(self.num_workers, max(n, 1))
        ctx = multiprocessing.get_context("spawn")
        # children must never claim the TPU chip or init a TPU backend;
        # env is captured at spawn time, so pin and restore around start()
        saved = {k: os.environ.get(k)
                 for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        procs, conns = [], []
        try:
            for w in range(W):
                rd, wr = ctx.Pipe(duplex=False)
                assigned = list(enumerate(batches))[w::W]
                p = ctx.Process(
                    target=_process_worker,
                    args=(wr, self.dataset, self.collate_fn,
                          self.worker_init_fn, w, assigned),
                    daemon=True)
                p.start()
                wr.close()  # parent keeps only the read end
                procs.append(p)
                conns.append(rd)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

        out_q: "queue.Queue" = queue.Queue(
            maxsize=W * self.prefetch_factor)
        DONE = object()

        def pull(conn):
            try:
                while True:
                    i, data = pickle.loads(conn.recv_bytes())
                    if i is None:
                        return
                    out_q.put((i, data))
            except (EOFError, OSError):
                # EOF: worker exited (normal after its DONE frame, or died —
                # the liveness check below reports short delivery). OSError:
                # consumer finished early and closed our read end mid-recv.
                pass
            finally:
                out_q.put((None, DONE))

        pullers = [threading.Thread(target=pull, args=(c,), daemon=True)
                   for c in conns]
        for t in pullers:
            t.start()
        try:
            results, want, live = {}, 0, W
            while want < n:
                while want not in results:
                    if live == 0 and out_q.empty():
                        raise RuntimeError(
                            "DataLoader worker processes exited before "
                            "delivering all batches")
                    i, data = out_q.get()
                    if data is DONE:
                        live -= 1
                        continue
                    if i == -1:
                        raise data  # exception forwarded from a worker
                    results[i] = data
                data = results.pop(want)
                yield data
                want += 1
        finally:
            for c in conns:
                c.close()
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    def __iter__(self):
        from ..framework.tensor import Tensor
        import jax

        def to_tensors(batch):
            if isinstance(batch, (tuple, list)):
                return [to_tensors(b) for b in batch]
            if isinstance(batch, dict):
                return {k: to_tensors(v) for k, v in batch.items()}
            if self.to_device:
                return Tensor._wrap(jax.device_put(batch))
            return Tensor._wrap(batch)

        # double buffer: device transfer of batch i+1 overlaps consumption of i
        prev = None
        for np_batch in self._batches_np():
            cur = to_tensors(np_batch)
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev
