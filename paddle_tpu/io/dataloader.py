"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py).

Host pipeline: sample indices → worker pool assembles numpy batches →
bounded prefetch queue → ``jax.device_put`` double-buffering. Divergence from
the reference, by design: workers are *threads*, not forked processes — the
numpy/PIL work they do releases the GIL, fork is hostile to a live PJRT
client, and the transfer overlap (the thing the reference's pin-memory thread
buys) comes from device_put being async.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    """Stack samples into batched arrays (reference:
    python/paddle/io/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    from ..framework.tensor import Tensor

    if isinstance(sample, Tensor):
        return np.stack([t.numpy() for t in batch])
    return np.asarray(batch)


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler: Optional[BatchSampler] = None,
                 batch_size=1, shuffle=False, drop_last=False,
                 collate_fn: Optional[Callable] = None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, to_device=True):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.worker_init_fn = worker_init_fn
        self.to_device = to_device
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    # ------------------------------------------------------------------ iter
    def _batches_np(self):
        """Yield collated numpy batches (worker-pool or inline)."""
        if self._iterable:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not self.drop_last:
                yield self.collate_fn(buf)
            return

        index_iter = iter(self.batch_sampler)
        if self.num_workers == 0:
            for idxs in index_iter:
                yield self.collate_fn([self.dataset[i] for i in idxs])
            return

        # thread workers: fetch batches concurrently, deliver in order
        out_q: "queue.Queue" = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        batches = list(index_iter)
        n = len(batches)
        results = {}
        lock = threading.Lock()
        next_fetch = [0]
        stop = threading.Event()

        def worker(wid):
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while not stop.is_set():
                with lock:
                    i = next_fetch[0]
                    if i >= n:
                        return
                    next_fetch[0] = i + 1
                try:
                    data = self.collate_fn([self.dataset[j] for j in batches[i]])
                    out_q.put((i, data))
                except Exception as e:  # surface in consumer
                    out_q.put((i, e))

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(self.num_workers)
        ]
        for t in threads:
            t.start()
        try:
            want = 0
            while want < n:
                while want not in results:
                    i, data = out_q.get()
                    results[i] = data
                data = results.pop(want)
                if isinstance(data, Exception):
                    raise data
                yield data
                want += 1
        finally:
            stop.set()

    def __iter__(self):
        from ..framework.tensor import Tensor
        import jax

        def to_tensors(batch):
            if isinstance(batch, (tuple, list)):
                return [to_tensors(b) for b in batch]
            if isinstance(batch, dict):
                return {k: to_tensors(v) for k, v in batch.items()}
            if self.to_device:
                return Tensor._wrap(jax.device_put(batch))
            return Tensor._wrap(batch)

        # double buffer: device transfer of batch i+1 overlaps consumption of i
        prev = None
        for np_batch in self._batches_np():
            cur = to_tensors(np_batch)
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev
