"""Dataset abstractions (reference: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np


class Dataset:
    """Map-style dataset: implement ``__getitem__`` and ``__len__``."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    """Stream-style dataset: implement ``__iter__``."""

    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset does not support indexing")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        from ..framework.tensor import Tensor

        arrs = []
        for t in tensors:
            if isinstance(t, Tensor):
                arrs.append(t.numpy())
            else:
                arrs.append(np.asarray(t))
        n = len(arrs[0])
        if any(len(a) != n for a in arrs):
            raise ValueError("all tensors must share dim 0")
        self._arrays = arrs

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self._arrays)

    def __len__(self):
        return len(self._arrays[0])


class ComposeDataset(Dataset):
    """Zip datasets column-wise."""

    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("empty datasets")
        n = len(self.datasets[0])
        if any(len(d) != n for d in self.datasets):
            raise ValueError("datasets must have equal length")

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    """Concatenate iterable datasets row-wise."""

    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: Iterable[Dataset]):
        self.datasets = list(datasets)
        self._cum: List[int] = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self._cum.append(total)

    def __len__(self):
        return self._cum[-1] if self._cum else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self._cum, idx)
        prev = self._cum[di - 1] if di else 0
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None):
    total = sum(lengths)
    if total != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    rng = np.random.default_rng(None if generator is None else generator)
    perm = rng.permutation(total)
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off : off + n].tolist()))
        off += n
    return out
