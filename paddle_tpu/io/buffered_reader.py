"""Buffered reader over the native ring buffer (reference:
paddle/fluid/operators/reader/buffered_reader.cc — the C++ prefetch
double-buffer; SURVEY.md B6 "worker-pool design feeding jax.device_put with
double-buffering").

``BufferedReader(iterable)`` runs the source on a producer thread and hands
numpy-batch payloads through the native C++ ring (memcpy outside the GIL);
without a toolchain it degrades to a queue.Queue with identical semantics.
"""
from __future__ import annotations

import ctypes
import pickle
import queue
import threading
from typing import Iterable, Iterator, Optional

__all__ = ["BufferedReader"]

_SENTINEL_ERR = b"\x01"
_PAYLOAD = b"\x00"


def _ring_lib():
    from ..native import load

    lib = load("ring_buffer", ["ring_buffer.cc"])
    if lib is None:
        return None
    lib.rb_create.restype = ctypes.c_void_p
    lib.rb_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.rb_push.restype = ctypes.c_int64
    lib.rb_push.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_uint8),
                            ctypes.c_uint64, ctypes.c_int64]
    lib.rb_pop.restype = ctypes.c_int64
    lib.rb_pop.argtypes = [ctypes.c_void_p,
                           ctypes.POINTER(ctypes.c_uint8),
                           ctypes.c_uint64, ctypes.c_int64]
    lib.rb_peek_len.restype = ctypes.c_int64
    lib.rb_peek_len.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.rb_close.argtypes = [ctypes.c_void_p]
    lib.rb_destroy.argtypes = [ctypes.c_void_p]
    return lib


class BufferedReader:
    """Iterate ``source`` with ``capacity`` batches of lookahead."""

    def __init__(self, source: Iterable, capacity: int = 2,
                 use_native: Optional[bool] = None,
                 slot_bytes: int = 1 << 20):
        self._source = source
        self._capacity = max(1, int(capacity))
        self._slot_bytes = max(1, int(slot_bytes))
        lib = None
        if use_native is not False:
            lib = _ring_lib()
            if lib is None and use_native is True:
                raise RuntimeError("native ring_buffer unavailable")
        self._lib = lib
        self.backend = "native" if lib is not None else "python"

    # ---------------------------------------------------------------- iter
    def __iter__(self) -> Iterator:
        if self._lib is None:
            return self._iter_python()
        return self._iter_native()

    def _iter_python(self):
        q: "queue.Queue" = queue.Queue(maxsize=self._capacity)
        DONE = object()

        def produce():
            try:
                for item in self._source:
                    q.put(item)
                q.put(DONE)
            except BaseException as e:  # surfaced on the consumer side
                q.put(e)

        t = threading.Thread(target=produce, daemon=True,
                             name="buffered-reader")
        t.start()
        while True:
            item = q.get()
            if item is DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def _iter_native(self):
        lib = self._lib
        h = lib.rb_create(self._slot_bytes, self._capacity)
        if not h:
            yield from self._iter_python()
            return

        def produce():
            try:
                for item in self._source:
                    payload = _PAYLOAD + pickle.dumps(
                        item, protocol=pickle.HIGHEST_PROTOCOL)
                    buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(
                        payload)
                    if lib.rb_push(h, buf, len(payload), -1) != 0:
                        # -2: consumer closed the ring (abandoned iteration)
                        # — stop draining the source promptly so the
                        # consumer's join() succeeds and the ring is freed
                        return
            except BaseException as e:
                payload = _SENTINEL_ERR + pickle.dumps(e)
                buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(
                    payload)
                lib.rb_push(h, buf, len(payload), -1)
            finally:
                lib.rb_close(h)

        t = threading.Thread(target=produce, daemon=True,
                             name="buffered-reader-native")
        t.start()
        try:
            while True:
                n = lib.rb_peek_len(h, -1)
                if n == -2:  # closed + drained
                    return
                out = (ctypes.c_uint8 * max(int(n), 1))()
                got = lib.rb_pop(h, out, len(out), -1)
                if got == -2:
                    return
                raw = bytes(out[:got])
                if raw[:1] == _SENTINEL_ERR:
                    raise pickle.loads(raw[1:])
                yield pickle.loads(raw[1:])
        finally:
            lib.rb_close(h)
            t.join(timeout=5)
            if t.is_alive():
                # The producer is still blocked inside the source iterator
                # and may yet call rb_push on this handle; freeing it now
                # would be a use-after-free in native code. Leak the (small)
                # ring instead — rb_close already unblocked its next push.
                pass
            else:
                lib.rb_destroy(h)
