"""paddle_tpu.io — datasets + DataLoader (reference: python/paddle/io/).

TPU-native data path (SURVEY.md B6): the reference's multiprocess worker pool
+ pinned-memory thread feeding a GPU stream becomes a host-side worker pool
feeding ``jax.device_put`` with double buffering — device transfer overlaps
host batch assembly, which is what hides input latency on TPU (there is no
"pin memory"; PJRT handles the HBM staging).
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    WeightedRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .buffered_reader import BufferedReader  # noqa: F401
