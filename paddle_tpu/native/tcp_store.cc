// TCPStore — native rendezvous key-value store.
//
// Reference parity: paddle/fluid/distributed/store/tcp_store.cc (+ store.h,
// tcp_utils.cc) — the socket KV store rank 0 hosts for NCCL bootstrap. Here
// it backs paddle_tpu.distributed.TCPStore: the control-plane store used
// before jax.distributed's coordination service exists (launcher rendezvous,
// eager barriers, elastic membership counts).
//
// Design: single acceptor thread + one thread per connection; an in-memory
// map<string, vector<uint8>> guarded by a mutex + condition_variable so GET
// can block until a key appears (the reference's Wait semantics). Wire
// protocol (little-endian):
//   request : u8 op | u32 klen | key bytes | u32 vlen | value bytes
//   response: i64 status/arith | u32 vlen | value bytes
//   ops: 0 SET, 1 GET(blocking, vlen=timeout_ms), 2 ADD(i64 delta in value),
//        3 CHECK (returns 1 if key exists), 4 DELETE.
//
// Exposed as a flat C ABI for ctypes (no pybind11 in this image).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::map<std::string, std::vector<uint8_t>> data;
  std::mutex mu;
  std::condition_variable cv;
};

struct Server {
  int listen_fd = -1;
  std::atomic<bool> stop{false};
  std::thread acceptor;
  std::mutex conn_mu;                 // guards workers + client_fds
  std::vector<std::thread> workers;   // mutated by acceptor, joined once
  std::vector<int> client_fds;
  Store store;
  ~Server() { shutdown(); }

  void shutdown() {
    bool expected = false;
    if (!stop.compare_exchange_strong(expected, true)) return;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
    }
    if (acceptor.joinable()) acceptor.join();  // no more workers spawn now
    {
      // wake blocked GET waiters and unblock recv()s
      std::lock_guard<std::mutex> lk(store.mu);
      store.cv.notify_all();
    }
    std::vector<std::thread> ws;
    {
      std::lock_guard<std::mutex> lk(conn_mu);
      for (int fd : client_fds) ::shutdown(fd, SHUT_RDWR);
      ws.swap(workers);
    }
    for (auto& w : ws)
      if (w.joinable()) w.join();
  }
};

bool read_n(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_n(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void serve_conn(Server* srv, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t op;
    uint32_t klen, vlen;
    if (!read_n(fd, &op, 1) || !read_n(fd, &klen, 4)) break;
    if (klen > (1u << 20)) break;
    std::string key(klen, '\0');
    if (!read_n(fd, key.data(), klen) || !read_n(fd, &vlen, 4)) break;
    if (vlen > (1u << 30)) break;
    std::vector<uint8_t> val(vlen);
    if (vlen && !read_n(fd, val.data(), vlen)) break;

    int64_t status = 0;
    std::vector<uint8_t> out;
    Store& st = srv->store;
    switch (op) {
      case 0: {  // SET
        std::lock_guard<std::mutex> lk(st.mu);
        st.data[key] = std::move(val);
        st.cv.notify_all();
        break;
      }
      case 1: {  // GET with timeout_ms encoded as the value payload (i64)
        int64_t timeout_ms = -1;
        if (val.size() == 8) std::memcpy(&timeout_ms, val.data(), 8);
        std::unique_lock<std::mutex> lk(st.mu);
        auto ready = [&] { return st.data.count(key) || srv->stop.load(); };
        if (timeout_ms < 0) {
          st.cv.wait(lk, ready);
        } else if (!st.cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   ready)) {
          status = -2;  // timeout
        }
        if (status == 0 && st.data.count(key)) {
          out = st.data[key];
        } else if (status == 0) {
          status = -1;  // server stopping
        }
        break;
      }
      case 2: {  // ADD (i64 delta) -> new value, stored as decimal string
        int64_t delta = 0;
        if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
        std::lock_guard<std::mutex> lk(st.mu);
        int64_t cur = 0;
        auto it = st.data.find(key);
        if (it != st.data.end())
          cur = std::strtoll(
              std::string(it->second.begin(), it->second.end()).c_str(),
              nullptr, 10);
        cur += delta;
        std::string s = std::to_string(cur);
        st.data[key].assign(s.begin(), s.end());
        status = cur;
        st.cv.notify_all();
        break;
      }
      case 3: {  // CHECK
        std::lock_guard<std::mutex> lk(st.mu);
        status = st.data.count(key) ? 1 : 0;
        break;
      }
      case 4: {  // DELETE
        std::lock_guard<std::mutex> lk(st.mu);
        status = st.data.erase(key) ? 1 : 0;
        break;
      }
      default:
        status = -100;
    }
    uint32_t olen = static_cast<uint32_t>(out.size());
    if (!write_n(fd, &status, 8) || !write_n(fd, &olen, 4)) break;
    if (olen && !write_n(fd, out.data(), olen)) break;
  }
  {
    // Deregister before close: shutdown() replays ::shutdown over
    // client_fds, and a stale entry could hit an unrelated descriptor the
    // process has since reused under the same number.
    std::lock_guard<std::mutex> lk(srv->conn_mu);
    auto& v = srv->client_fds;
    v.erase(std::remove(v.begin(), v.end(), fd), v.end());
  }
  ::close(fd);
}

}  // namespace

extern "C" {

// ---- server ----
void* ts_server_start(int port) {
  auto* srv = new Server();
  srv->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (srv->listen_fd < 0) {
    delete srv;
    return nullptr;
  }
  int one = 1;
  setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(srv->listen_fd, 128) != 0) {
    ::close(srv->listen_fd);
    delete srv;
    return nullptr;
  }
  srv->acceptor = std::thread([srv] {
    while (!srv->stop.load()) {
      int fd = ::accept(srv->listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      std::lock_guard<std::mutex> lk(srv->conn_mu);
      if (srv->stop.load()) {
        ::close(fd);
        break;
      }
      srv->client_fds.push_back(fd);
      srv->workers.emplace_back(serve_conn, srv, fd);
    }
  });
  return srv;
}

void ts_server_stop(void* h) {
  auto* srv = static_cast<Server*>(h);
  if (srv) {
    srv->shutdown();
    delete srv;
  }
}

// ---- client ----
void* ts_client_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  // bounded connect retries (the server may come up a moment later — the
  // reference retries for ~15 min; callers pass their own budget)
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms : 1);
  while (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
         0) {
    if (std::chrono::steady_clock::now() >= deadline) {
      ::close(fd);
      return nullptr;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    ::close(fd);
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return reinterpret_cast<void*>(static_cast<intptr_t>(fd) + 1);
}

void ts_client_close(void* h) {
  if (h) ::close(static_cast<int>(reinterpret_cast<intptr_t>(h) - 1));
}

static int64_t roundtrip(void* h, uint8_t op, const char* key,
                         const uint8_t* val, uint32_t vlen, uint8_t* out,
                         uint32_t out_cap, uint32_t* out_len) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(h) - 1);
  uint32_t klen = static_cast<uint32_t>(std::strlen(key));
  if (!write_n(fd, &op, 1) || !write_n(fd, &klen, 4) ||
      !write_n(fd, key, klen) || !write_n(fd, &vlen, 4) ||
      (vlen && !write_n(fd, val, vlen)))
    return -200;
  int64_t status;
  uint32_t olen;
  if (!read_n(fd, &status, 8) || !read_n(fd, &olen, 4)) return -201;
  if (out_len) *out_len = olen;
  if (olen) {
    std::vector<uint8_t> tmp(olen);
    if (!read_n(fd, tmp.data(), olen)) return -202;
    if (out && out_cap >= olen) std::memcpy(out, tmp.data(), olen);
    else if (out) return -203;  // caller buffer too small
  }
  return status;
}

int64_t ts_set(void* h, const char* key, const uint8_t* val, uint32_t vlen) {
  return roundtrip(h, 0, key, val, vlen, nullptr, 0, nullptr);
}

int64_t ts_get(void* h, const char* key, int64_t timeout_ms, uint8_t* out,
               uint32_t out_cap, uint32_t* out_len) {
  return roundtrip(h, 1, key, reinterpret_cast<uint8_t*>(&timeout_ms), 8, out,
                   out_cap, out_len);
}

int64_t ts_add(void* h, const char* key, int64_t delta) {
  return roundtrip(h, 2, key, reinterpret_cast<uint8_t*>(&delta), 8, nullptr,
                   0, nullptr);
}

int64_t ts_check(void* h, const char* key) {
  return roundtrip(h, 3, key, nullptr, 0, nullptr, 0, nullptr);
}

int64_t ts_delete(void* h, const char* key) {
  return roundtrip(h, 4, key, nullptr, 0, nullptr, 0, nullptr);
}

}  // extern "C"
