"""Native (C++) runtime components, loaded via ctypes (SURVEY.md stance:
pybind11 is absent from this image — C ABI + ctypes is the binding layer).

Build-on-first-import with g++; artifacts cached under
``paddle_tpu/native/_build/``. Every native component has a pure-Python
fallback so the framework works without a toolchain (the reference requires
a full CMake build; we degrade gracefully instead).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

_HERE = os.path.dirname(__file__)
_BUILD = os.path.join(_HERE, "_build")
_lock = threading.Lock()
_libs = {}


def _compile(name: str, sources) -> Optional[str]:
    """g++ -O2 -shared; returns .so path or None when unavailable.

    Compiles to a per-process temp path and os.rename()s into place so
    sibling processes racing on a cold cache never dlopen a half-written
    .so (rename is atomic within a filesystem)."""
    so = os.path.join(_BUILD, f"lib{name}.so")
    srcs = [os.path.join(_HERE, s) for s in sources]
    if os.path.exists(so) and all(
        os.path.getmtime(so) >= os.path.getmtime(s) for s in srcs
    ):
        return so
    os.makedirs(_BUILD, exist_ok=True)
    tmp = f"{so}.tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
           "-o", tmp, *srcs]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if r.returncode != 0:
        print(f"[paddle_tpu.native] build of {name} failed:\n{r.stderr}",
              file=sys.stderr)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    try:
        os.rename(tmp, so)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        if not os.path.exists(so):
            return None
    return so


def load(name: str, sources) -> Optional[ctypes.CDLL]:
    """Build (if needed) + dlopen a native component; None on failure
    (callers engage their pure-Python fallback)."""
    with _lock:
        if name in _libs:
            return _libs[name]
        so = _compile(name, sources)
        try:
            lib = ctypes.CDLL(so) if so else None
        except OSError as e:
            print(f"[paddle_tpu.native] dlopen of {name} failed: {e}",
                  file=sys.stderr)
            lib = None
        _libs[name] = lib
        return lib


def tcp_store_lib() -> Optional[ctypes.CDLL]:
    lib = load("tcp_store", ["tcp_store.cc"])
    if lib is None:
        return None
    lib.ts_server_start.restype = ctypes.c_void_p
    lib.ts_server_start.argtypes = [ctypes.c_int]
    lib.ts_server_stop.argtypes = [ctypes.c_void_p]
    lib.ts_client_connect.restype = ctypes.c_void_p
    lib.ts_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                      ctypes.c_int]
    lib.ts_client_close.argtypes = [ctypes.c_void_p]
    lib.ts_set.restype = ctypes.c_int64
    lib.ts_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32]
    lib.ts_get.restype = ctypes.c_int64
    lib.ts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                           ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32,
                           ctypes.POINTER(ctypes.c_uint32)]
    lib.ts_add.restype = ctypes.c_int64
    lib.ts_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.ts_check.restype = ctypes.c_int64
    lib.ts_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ts_delete.restype = ctypes.c_int64
    lib.ts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return lib
