// Byte-slot ring buffer — native core of the buffered reader.
//
// Reference parity: paddle/fluid/operators/reader/buffered_reader.cc — the
// C++ double-buffer between dataset workers and the device feed. Here it is
// a bounded MPSC ring of byte slots with mutex+condvar blocking on both
// ends; the memcpy of batch payloads happens inside these C calls, i.e.
// OUTSIDE the Python GIL (ctypes releases it for the duration of the call),
// so producer and consumer copy concurrently with Python-level work.
//
// C ABI (ctypes):
//   rb_create(slot_bytes, n_slots) -> handle   (slot_bytes = reserve hint;
//                                               slots grow to fit any push)
//   rb_push(h, data, len, timeout_ms) -> 0 | -1 timeout | -2 closed
//   rb_pop(h, out, cap, timeout_ms)  -> len | -1 timeout | -2 closed+empty | -3 cap
//   rb_close(h)    (producer side: consumers drain then see -2)
//   rb_destroy(h)

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Ring {
  std::vector<std::vector<uint8_t>> slots;
  std::vector<uint32_t> sizes;
  size_t head = 0, tail = 0, count = 0;
  bool closed = false;
  std::mutex mu;
  std::condition_variable not_full, not_empty;

  explicit Ring(size_t slot_bytes, size_t n) : slots(n), sizes(n, 0) {
    for (auto& s : slots) s.reserve(slot_bytes);
  }
};

template <typename Pred>
bool wait_on(std::condition_variable& cv, std::unique_lock<std::mutex>& lk,
             int64_t timeout_ms, Pred pred) {
  if (timeout_ms < 0) {
    cv.wait(lk, pred);
    return true;
  }
  return cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
}

}  // namespace

extern "C" {

void* rb_create(uint64_t slot_bytes, uint64_t n_slots) {
  if (n_slots == 0) return nullptr;
  return new Ring(slot_bytes, n_slots);
}

int64_t rb_push(void* h, const uint8_t* data, uint64_t len,
                int64_t timeout_ms) {
  auto* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  if (!wait_on(r->not_full, lk, timeout_ms,
               [&] { return r->count < r->slots.size() || r->closed; }))
    return -1;
  if (r->closed) return -2;
  auto& slot = r->slots[r->tail];
  slot.resize(len);
  if (len) std::memcpy(slot.data(), data, len);
  r->sizes[r->tail] = static_cast<uint32_t>(len);
  r->tail = (r->tail + 1) % r->slots.size();
  ++r->count;
  r->not_empty.notify_one();
  return 0;
}

int64_t rb_pop(void* h, uint8_t* out, uint64_t cap, int64_t timeout_ms) {
  auto* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  if (!wait_on(r->not_empty, lk, timeout_ms,
               [&] { return r->count > 0 || r->closed; }))
    return -1;
  if (r->count == 0) return -2;  // closed and drained
  uint32_t len = r->sizes[r->head];
  if (len > cap) return -3;
  if (len) std::memcpy(out, r->slots[r->head].data(), len);
  r->head = (r->head + 1) % r->slots.size();
  --r->count;
  r->not_full.notify_one();
  return static_cast<int64_t>(len);
}

int64_t rb_peek_len(void* h, int64_t timeout_ms) {
  auto* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  if (!wait_on(r->not_empty, lk, timeout_ms,
               [&] { return r->count > 0 || r->closed; }))
    return -1;
  if (r->count == 0) return -2;
  return static_cast<int64_t>(r->sizes[r->head]);
}

void rb_close(void* h) {
  auto* r = static_cast<Ring*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  r->closed = true;
  r->not_empty.notify_all();
  r->not_full.notify_all();
}

void rb_destroy(void* h) { delete static_cast<Ring*>(h); }

}  // extern "C"
