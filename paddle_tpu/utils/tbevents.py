"""Native TensorBoard event-file writer — no torch, no tensorboard pip.

Reference capability: the VisualDL scalar logging backend
(python/paddle/hapi/callbacks.py VisualDL; VisualDL itself stores its own
format, but the ecosystem-standard consumer is TensorBoard). Round 3
review flagged depending on ``torch.utils.tensorboard`` — a competing
framework — as the primary backend of this callback; the wire formats
involved are simple enough to emit directly:

* **TFRecord framing**: ``uint64 length | masked crc32c(length) |
  payload | masked crc32c(payload)`` per record;
* **Event protobuf** (tensorflow/core/util/event.proto), scalar subset:
  ``wall_time (1, double) | step (2, int64) | file_version (3, string) |
  summary (5, Summary{ repeated Value{ tag (1), simple_value (2) } })``.

Files written here open in stock TensorBoard.
"""
from __future__ import annotations

import os
import struct
import time

__all__ = ["EventFileWriter"]

# ---------------------------------------------------------------- crc32c
_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE:
        return _CRC_TABLE
    poly = 0x82F63B78  # Castagnoli, reflected
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (poly if crc & 1 else 0)
        table.append(crc)
    _CRC_TABLE = table
    return table


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------- protobuf encoding


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3) + _varint(value)


def _field_double(num: int, value: float) -> bytes:
    return _varint((num << 3) | 1) + struct.pack("<d", value)


def _field_float(num: int, value: float) -> bytes:
    return _varint((num << 3) | 5) + struct.pack("<f", value)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _scalar_event(tag: str, value: float, step: int,
                  wall_time: float) -> bytes:
    val = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    summary = _field_bytes(1, val)
    return (_field_double(1, wall_time)
            + _field_varint(2, int(step))
            + _field_bytes(5, summary))


def _version_event(wall_time: float) -> bytes:
    return (_field_double(1, wall_time)
            + _field_bytes(3, b"brain.Event:2"))


# ---------------------------------------------------------------- writer


class EventFileWriter:
    """Minimal ``SummaryWriter``-alike: ``add_scalar`` + ``close``.
    Writing after ``close()`` reopens a fresh event file in the same
    log_dir (torch's SummaryWriter behaves this way, and the hapi
    VisualDL callback relies on it across fit -> evaluate)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._f = None
        self._open()

    def _open(self):
        os.makedirs(self.log_dir, exist_ok=True)
        now = time.time()
        # pid + a per-writer nonce keep reopened files distinct
        name = (f"events.out.tfevents.{int(now)}."
                f"{os.uname().nodename}.{os.getpid()}."
                f"{id(self) & 0xFFFF}")
        self._f = open(os.path.join(self.log_dir, name), "ab")
        self._record(_version_event(now))

    def _record(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))
        self._f.flush()

    def add_scalar(self, tag: str, value: float, step: int):
        if self._f is None:
            self._open()
        self._record(_scalar_event(tag, value, step, time.time()))

    def flush(self):
        """os-level flush so a crash right after cannot lose events
        (_record already flushes the python buffer per write)."""
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None
