"""paddle.utils parity (reference: python/paddle/utils/)."""
from . import unique_name  # noqa: F401
from .lazy_import import try_import  # noqa: F401


def run_check():
    """paddle.utils.run_check parity: verify the framework can compute."""
    import jax
    import jax.numpy as jnp

    n = jax.device_count()
    x = jnp.ones((128, 128))
    val = float(jax.device_get(jnp.sum(x @ x)))
    assert val == 128.0 * 128 * 128
    print(f"paddle_tpu is installed successfully! {n} device(s): "
          f"{[d.device_kind for d in jax.devices()]}")


def deprecated(update_to="", since="", reason=""):
    import functools
    import warnings

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            warnings.warn(
                f"{fn.__name__} is deprecated since {since}: {reason} "
                f"{'Use ' + update_to if update_to else ''}",
                DeprecationWarning, stacklevel=2,
            )
            return fn(*args, **kwargs)

        return wrapper

    return decorator
from . import cpp_extension  # noqa: F401
