"""try_import (reference: python/paddle/utils/lazy_import.py)."""
import importlib


def try_import(module_name: str, err_msg: str = None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed "
            "(no-network environment: dependency must be baked in)"
        )
