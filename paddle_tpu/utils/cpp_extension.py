"""paddle.utils.cpp_extension parity (reference:
python/paddle/utils/cpp_extension/ — JIT-compile user C++/CUDA ops and
register them; SURVEY.md A25: "jax.ffi / Pallas custom-kernel registration
helper").

TPU stance: device kernels are Pallas (see paddle_tpu/ops/pallas/); this
module covers the HOST-side C++ extension path — compile a shared object
with the baked toolchain and hand back a ctypes handle (the same machinery
that builds the native TCPStore). CUDA sources are rejected explicitly.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional, Sequence

__all__ = ["load", "load_ffi", "CppExtension", "CUDAExtension"]


def load(name: str, sources: Sequence[str], extra_cxx_cflags=None,
         extra_cuda_cflags=None, extra_ldflags=None, extra_include_paths=None,
         build_directory: Optional[str] = None, verbose: bool = False):
    """JIT-compile C++ ``sources`` into a shared object and dlopen it.
    Returns the ctypes.CDLL (callers declare argtypes/restypes, or wrap via
    jax.ffi for in-graph custom calls)."""
    if any(str(s).endswith((".cu", ".cuh")) for s in sources):
        raise ValueError(
            "CUDA sources are not buildable on TPU — write device kernels "
            "in Pallas (paddle_tpu/ops/pallas) and host code in C++")
    import subprocess
    import sys

    build = build_directory or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    os.makedirs(build, exist_ok=True)
    srcs = [os.path.abspath(s) for s in sources]
    # cache key covers the FULL build configuration, not just the name —
    # same-name loads with different sources/flags must not collide
    import hashlib

    cfg = repr((sorted(srcs), extra_cxx_cflags, extra_ldflags,
                extra_include_paths))
    tag = hashlib.sha1(cfg.encode()).hexdigest()[:10]
    so = os.path.join(build, f"lib{name}.{tag}.so")
    if not (os.path.exists(so) and all(
            os.path.getmtime(so) >= os.path.getmtime(s) for s in srcs)):
        # temp + atomic rename: concurrent processes on a cold cache must
        # never dlopen a partially-written .so
        tmp = f"{so}.tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread"]
        for inc in (extra_include_paths or []):
            cmd += ["-I", inc]
        cmd += (extra_cxx_cflags or [])
        cmd += ["-o", tmp, *srcs]
        cmd += (extra_ldflags or [])
        if verbose:
            print(" ".join(cmd), file=sys.stderr)
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if r.returncode != 0:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise RuntimeError(f"cpp_extension build failed:\n{r.stderr}")
        try:
            os.rename(tmp, so)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            if not os.path.exists(so):
                raise
    return ctypes.CDLL(so)


def load_ffi(name: str, sources: Sequence[str], functions: Sequence[str],
             platform: str = "cpu", **load_kwargs):
    """Compile C++ ``sources`` implementing XLA FFI handlers and register
    each symbol in ``functions`` as an XLA custom-call target — the
    registration path the reference provides through paddle/phi/capi
    (SURVEY.md A7: out-of-tree kernels entering dispatch) and
    op_meta_info.h custom ops (A25), here entering XLA's dispatch so the op
    is usable INSIDE jit.

    Handlers use the jaxlib-shipped headers (xla/ffi/api/ffi.h +
    XLA_FFI_DEFINE_HANDLER_SYMBOL); targets are registered as
    ``{name}.{function}``. Returns ``{function: caller}`` where
    ``caller(result_shape_dtypes, *args, **attrs)`` invokes
    ``jax.ffi.ffi_call``. ``platform`` is "cpu": XLA custom calls execute on
    the host even in TPU programs (TPU device code stays Pallas)."""
    import jax

    # jax.ffi graduated from jax.extend.ffi after 0.4.x; same surface
    try:
        jax_ffi = jax.ffi
    except AttributeError:
        from jax.extend import ffi as jax_ffi

    inc = list(load_kwargs.pop("extra_include_paths", []) or [])
    inc.append(jax_ffi.include_dir())
    lib = load(name, sources, extra_include_paths=inc, **load_kwargs)

    callers = {}
    for fn_name in functions:
        sym = getattr(lib, fn_name)
        target = f"{name}.{fn_name}"
        # XLA rejects re-registering a target name at a different address;
        # same build → reuse, different build of the same name → a
        # uniquified target (the reference's registry similarly keys on the
        # registering module)
        seen = _ffi_registry.get((target, platform))
        if seen is not None and seen != lib._name:
            n = 1
            while _ffi_registry.get((f"{target}#{n}", platform),
                                    lib._name) != lib._name:
                n += 1
            target = f"{target}#{n}"
            seen = _ffi_registry.get((target, platform))
        if seen is None:
            jax_ffi.register_ffi_target(target, jax_ffi.pycapsule(sym),
                                        platform=platform)
            _ffi_registry[(target, platform)] = lib._name

        def caller(result_shape_dtypes, *args, _target=target, **attrs):
            return jax_ffi.ffi_call(_target, result_shape_dtypes)(
                *args, **attrs)

        callers[fn_name] = caller
    return callers


_ffi_registry: dict = {}


class CppExtension:
    """setup()-style descriptor parity (reference CppExtension)."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = list(sources)
        self.kwargs = kwargs


def CUDAExtension(*args, **kwargs):  # pragma: no cover
    raise NotImplementedError(
        "CUDAExtension is CUDA-only; on TPU write Pallas kernels "
        "(paddle_tpu/ops/pallas) or host C++ via CppExtension/load")
