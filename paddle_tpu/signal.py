"""paddle.signal parity (reference: python/paddle/signal.py — stft/istft
over the frame + fft kernels)."""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .framework.tensor import Tensor, apply_op

__all__ = ["stft", "istft", "frame", "overlap_add"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def frame(x, frame_length: int, hop_length: int, axis: int = -1, name=None):
    """Slide frames of ``frame_length`` every ``hop_length`` (reference:
    paddle.signal.frame; output [..., frame_length, num_frames])."""
    def fn(a):
        moved = axis not in (-1, a.ndim - 1)
        if moved:
            a = jnp.moveaxis(a, axis, -1)
        n = a.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        out = a[..., idx]  # [..., num, frame_length]
        out = jnp.swapaxes(out, -1, -2)  # [..., frame_length, num]
        if moved:
            # restore the reference layout: framed axis pair goes back where
            # the original axis was ((frame_length, num_frames) leading for
            # axis=0 — paddle.signal.frame semantics)
            out = jnp.moveaxis(out, (-2, -1), (axis, axis + 1))
        return out

    return apply_op(fn, x)


def overlap_add(x, hop_length: int, axis: int = -1, name=None):
    """Inverse of frame (reference: paddle.signal.overlap_add; input
    [..., frame_length, num_frames])."""
    def fn(a):
        frame_length, num = a.shape[-2], a.shape[-1]
        n = (num - 1) * hop_length + frame_length
        out = jnp.zeros(a.shape[:-2] + (n,), a.dtype)
        for i in range(num):  # static unroll: num is a trace constant
            out = out.at[..., i * hop_length: i * hop_length + frame_length
                         ].add(a[..., i])
        return out

    return apply_op(fn, x)


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None):
    """Reference: paddle.signal.stft. Returns [..., n_fft//2+1 or n_fft,
    num_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = _arr(window)

    def fn(a):
        w = (jnp.ones(win_length, a.dtype) if window is None else window)
        if win_length < n_fft:  # centre-pad window to n_fft
            lpad = (n_fft - win_length) // 2
            wp = jnp.zeros(n_fft, a.dtype).at[lpad:lpad + win_length].set(w)
        else:
            wp = w[:n_fft]
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        n = a.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = a[..., idx] * wp  # [..., num, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, num]

    return apply_op(fn, x)


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False,
          name=None):
    """Reference: paddle.signal.istft (overlap-add with window-square
    normalization)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = _arr(window)

    def fn(spec):
        w = (jnp.ones(win_length, jnp.float32) if window is None
             else window.astype(jnp.float32))
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            wp = jnp.zeros(n_fft, jnp.float32).at[
                lpad:lpad + win_length].set(w)
        else:
            wp = w[:n_fft]
        frames_fd = jnp.swapaxes(spec, -1, -2)  # [..., num, freq]
        frames = (jnp.fft.irfft(frames_fd, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(frames_fd, axis=-1).real)
        if normalized:
            frames = frames * jnp.sqrt(jnp.asarray(n_fft, frames.dtype))
        frames = frames * wp
        num = frames.shape[-2]
        n = (num - 1) * hop_length + n_fft
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        wsum = jnp.zeros(n, frames.dtype)
        for i in range(num):
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[..., sl].add(frames[..., i, :])
            wsum = wsum.at[sl].add(wp ** 2)
        out = out / jnp.where(wsum > 1e-10, wsum, 1.0)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    return apply_op(fn, x)
