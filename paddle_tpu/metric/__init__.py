"""Streaming metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    from ..framework.tensor import Tensor

    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        pred = _np(pred)
        label = _np(label)
        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = label[..., 0]
        top = np.argsort(-pred, axis=-1)[..., : self.maxk]
        correct = top == label[..., None]
        return correct

    def update(self, correct):
        correct = _np(correct)
        n = correct.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].any(axis=-1).sum()
            self.count[i] += n
        accs = self.total / np.maximum(self.count, 1)
        return accs[0] if len(self.topk) == 1 else accs

    def accumulate(self):
        accs = self.total / np.maximum(self.count, 1)
        return float(accs[0]) if len(self.topk) == 1 else [float(a) for a in accs]

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Auc(Metric):
    """Bucketed ROC-AUC (reference: paddle.metric.Auc num_thresholds)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2:
            preds = preds[:, -1]  # prob of positive class
        labels = _np(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        for i, l in zip(idx, labels):
            if l:
                self._pos[i] += 1
            else:
                self._neg[i] += 1

    def accumulate(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # sum over buckets of trapezoid areas, descending threshold
        tp = fp = 0
        area = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_tp = tp + self._pos[i]
            new_fp = fp + self._neg[i]
            area += (new_fp - fp) * (tp + new_tp) / 2.0
            tp, fp = new_tp, new_fp
        return float(area / (tot_pos * tot_neg))


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference: python/paddle/metric/metrics.py
    paddle.metric.accuracy)."""
    from ..framework.tensor import Tensor
    import jax.numpy as jnp

    pred = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    if lab.ndim == pred.ndim and lab.shape[-1] == 1:
        lab = lab[..., 0]
    _, top = jax.lax.top_k(pred, k)
    corr = (top == lab[..., None]).any(axis=-1)
    return Tensor._wrap(jnp.mean(corr.astype(jnp.float32)))


import jax  # noqa: E402  (used by accuracy)
