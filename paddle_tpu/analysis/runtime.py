"""Runtime companions to the static passes: tracer-leak guard and
thread-ownership guard.

Static analysis catches what it can see; these catch the rest at runtime.
``leak_guard`` arms ``jax.check_tracer_leaks`` around a compiled-path
entry so a leaked tracer (the runtime shadow of TPL401/TPL402) raises at
trace end instead of detonating later as an inscrutable
``UnexpectedTracerError`` far from the leak site.

``ownership_guard`` (ISSUE 19) is the dynamic twin of tpurace
(``analysis/ownership.py``, TPL1501–TPL1504): with the guard armed,
:func:`guard_object`-wrapped objects (Engine, CacheCoordinator,
PrefixCache, HostTier — :func:`guard_engine` wires all four) stamp the
owning thread on the FIRST attribute write after arming and raise a typed
:class:`OwnershipError` on any later write from a different thread.
Sanctioned channels stay invisible by construction: ``queue.Queue``
put/get, ``deque`` append/popleft and ``Event`` set/wait are METHOD
calls, not attribute writes, so the deque-out/queue-in contract the
static pass trusts is exactly the surface the runtime guard never
touches. Conversely, writes the static pass cannot see —
``setattr(obj, name, v)``, reflection, aliases through untyped
containers — hit ``__setattr__`` like any other write and are caught
(the ``racey-worker-write`` fault point proves this in chaos).

Honest limits: write-side only (a torn READ of a half-updated structure
is invisible — intercepting ``__getattribute__`` would blow the <2%
``ownership_guard_overhead_frac`` budget), per-attribute (two attrs of
one object may legitimately have different owners), and ownership is
re-stamped at each arming, so construct-then-publish hand-offs are fine
as long as publication precedes arming.

Both guards are opt-in, because checking costs fast paths: set
``PADDLE_TPU_CHECK_TRACERS=1`` / ``PADDLE_TPU_CHECK_OWNERSHIP=1`` in the
environment (or the ``FLAGS_check_tracers`` / ``FLAGS_check_ownership``
flags) — CI and tests do; the production hot path keeps them off.
"""
from __future__ import annotations

import contextlib
import threading

__all__ = ["leak_guard", "tracer_checks_enabled", "TracerLeakError",
           "ownership_guard", "ownership_checks_enabled", "OwnershipError",
           "guard_object", "guard_engine", "thread_domain"]


class TracerLeakError(RuntimeError):
    """A traced value escaped its trace (see tpulint TPL401/TPL402)."""


def tracer_checks_enabled() -> bool:
    from ..framework import flags

    return bool(flags.get_flags("FLAGS_check_tracers")["FLAGS_check_tracers"])


@contextlib.contextmanager
def leak_guard(enabled: bool = None):
    """Hard-fail on tracers leaking out of the wrapped compiled region.

    ``enabled=None`` (the default) defers to the ``FLAGS_check_tracers``
    flag / ``PADDLE_TPU_CHECK_TRACERS`` env var, so production callers can
    wrap their jit entry points unconditionally and pay nothing unless the
    check is armed.
    """
    if enabled is None:
        enabled = tracer_checks_enabled()
    if not enabled:
        yield
        return
    import jax

    with jax.check_tracer_leaks():
        try:
            yield
        except Exception as e:
            if "leak" in str(e).lower() or "Tracer" in type(e).__name__:
                raise TracerLeakError(
                    "a traced value leaked out of the compiled region "
                    "(stored into a global/closure/container during trace). "
                    "Return the value from the traced function instead — "
                    "see tpulint rules TPL401/TPL402. Original error: "
                    f"{e}") from e
            raise


# --------------------------------------------------- thread-ownership guard


class OwnershipError(RuntimeError):
    """A guarded object's attribute was written from a thread that does
    not own it (see tpurace, rules TPL1501-TPL1504). Route the write
    through the object's sanctioned channel (job queue / completion
    deque / ``call_soon_threadsafe``) instead."""


def ownership_checks_enabled() -> bool:
    from ..framework import flags

    return bool(flags.get_flags(
        "FLAGS_check_ownership")["FLAGS_check_ownership"])


def thread_domain(name: str):
    """Declare the decorated function as the root of thread domain
    ``name`` for tpurace discovery — the escape hatch for entrypoints
    the structural discovery cannot see (callbacks registered with C
    extensions, signal handlers). Runtime no-op beyond tagging."""
    def deco(fn):
        tags = getattr(fn, "__tpu_thread_domains__", ())
        fn.__tpu_thread_domains__ = tags + (name,)
        return fn
    return deco


# armed > 0 while any ownership_guard() is active; gen bumps at each
# arming so owner stamps never survive one guarded region into the next
# (the engine thread of run A is not the engine thread of run B)
_OWNERSHIP = {"armed": 0, "gen": 0}


class _GuardRec:
    __slots__ = ("label", "exempt", "owners", "gen", "lock")

    def __init__(self, label, exempt):
        self.label = label
        self.exempt = frozenset(exempt)
        self.owners = {}          # attr -> owning Thread (this arming)
        self.gen = -1
        self.lock = threading.Lock()


_GUARDED_SUBCLASS = {}            # base class -> guarded subclass


def guard_object(obj, label: str = None, exempt=()):
    """Wrap ``obj`` so that, while :func:`ownership_guard` is armed,
    the first thread to write each attribute owns it and any other
    thread's write raises :class:`OwnershipError`. Write-side only and
    idempotent; ``exempt`` names attributes deliberately multi-writer
    under their own lock. Returns ``obj`` (the wrap swaps
    ``__class__`` to a dynamic subclass, so identity and isinstance
    are preserved)."""
    base = type(obj)
    if getattr(base, "_tpu_ownership_guarded", False):
        return obj
    sub = _GUARDED_SUBCLASS.get(base)
    if sub is None:
        def __setattr__(self, attr, value, _base=base):
            rec = self.__dict__.get("_tpu_guard_rec")
            if (rec is not None and _OWNERSHIP["armed"]
                    and not attr.startswith("__")
                    and attr != "_tpu_guard_rec"
                    and attr not in rec.exempt):
                me = threading.current_thread()
                with rec.lock:
                    if rec.gen != _OWNERSHIP["gen"]:
                        rec.owners.clear()
                        rec.gen = _OWNERSHIP["gen"]
                    owner = rec.owners.setdefault(attr, me)
                if owner is not me:
                    raise OwnershipError(
                        f"{rec.label}.{attr} is owned by thread "
                        f"{owner.name!r} (first writer under the armed "
                        f"guard) but was written from {me.name!r}: "
                        f"cross-thread write outside the sanctioned "
                        f"channels. Hand the value through the job "
                        f"queue / completion deque, hold the owning "
                        f"lock, or marshal via call_soon_threadsafe "
                        f"(tpurace TPL1501).")
            _base.__setattr__(self, attr, value)

        sub = type(f"{base.__name__}(ownership-guarded)", (base,), {
            "__setattr__": __setattr__,
            "_tpu_ownership_guarded": True,
            # dynamic subclass: keep pickling/repr pointing at the base
            "__module__": base.__module__,
        })
        _GUARDED_SUBCLASS[base] = sub
    object.__setattr__(obj, "_tpu_guard_rec",
                       _GuardRec(label or base.__name__, exempt))
    obj.__class__ = sub
    return obj


def guard_engine(engine):
    """Guard the serving stack's shared-ownership objects: the Engine
    itself plus its CacheCoordinator, PrefixCache, and HostTier (the
    objects the kv-tier channel contract protects). getattr-based so a
    tierless or cacheless engine guards whatever it actually has."""
    guard_object(engine, label="Engine")
    cache = getattr(engine, "_cache", None)
    if cache is not None:
        guard_object(cache, label="CacheCoordinator")
        pcache = getattr(cache, "pcache", None)
        if pcache is not None:
            guard_object(pcache, label="PrefixCache")
        tier = getattr(cache, "tier", None)
        if tier is not None:
            guard_object(tier, label="HostTier")
    return engine


@contextlib.contextmanager
def ownership_guard(enabled: bool = None):
    """Arm cross-thread write detection on every guarded object for the
    duration of the block. ``enabled=None`` defers to
    ``FLAGS_check_ownership`` / ``PADDLE_TPU_CHECK_OWNERSHIP``, so
    callers can wrap entry points unconditionally and pay nothing
    (one dict lookup per guarded write) unless the check is armed.
    Arm AFTER construction/hand-off: ownership stamps begin at the
    first write inside the armed region, so the constructor thread is
    never mistaken for the owner."""
    if enabled is None:
        enabled = ownership_checks_enabled()
    if not enabled:
        yield
        return
    _OWNERSHIP["gen"] += 1
    _OWNERSHIP["armed"] += 1
    try:
        yield
    finally:
        _OWNERSHIP["armed"] -= 1
