"""Runtime companion to tpulint: tracer-leak guard for the compiled path.

Static analysis catches what it can see; ``leak_guard`` catches the rest at
runtime by arming ``jax.check_tracer_leaks`` around a compiled-path entry.
A leaked tracer (a traced value stashed into module/closure state — the
runtime shadow of TPL401/TPL402) then raises at trace end instead of
detonating later as an inscrutable ``UnexpectedTracerError`` far from the
leak site.

Opt-in, because leak checking disables some tracing fast paths: set
``PADDLE_TPU_CHECK_TRACERS=1`` in the environment (or
``paddle.set_flags({"FLAGS_check_tracers": True})``) — CI and tests do; the
production hot path keeps it off.
"""
from __future__ import annotations

import contextlib

__all__ = ["leak_guard", "tracer_checks_enabled", "TracerLeakError"]


class TracerLeakError(RuntimeError):
    """A traced value escaped its trace (see tpulint TPL401/TPL402)."""


def tracer_checks_enabled() -> bool:
    from ..framework import flags

    return bool(flags.get_flags("FLAGS_check_tracers")["FLAGS_check_tracers"])


@contextlib.contextmanager
def leak_guard(enabled: bool = None):
    """Hard-fail on tracers leaking out of the wrapped compiled region.

    ``enabled=None`` (the default) defers to the ``FLAGS_check_tracers``
    flag / ``PADDLE_TPU_CHECK_TRACERS`` env var, so production callers can
    wrap their jit entry points unconditionally and pay nothing unless the
    check is armed.
    """
    if enabled is None:
        enabled = tracer_checks_enabled()
    if not enabled:
        yield
        return
    import jax

    with jax.check_tracer_leaks():
        try:
            yield
        except Exception as e:
            if "leak" in str(e).lower() or "Tracer" in type(e).__name__:
                raise TracerLeakError(
                    "a traced value leaked out of the compiled region "
                    "(stored into a global/closure/container during trace). "
                    "Return the value from the traced function instead — "
                    "see tpulint rules TPL401/TPL402. Original error: "
                    f"{e}") from e
            raise
