"""paddle_tpu.analysis — tpulint: trace-safety tooling for the compiled path.

Static side (pure stdlib, no jax import): an AST linter that finds
jit-breaking and recompile-forcing patterns — host syncs, impure RNG,
tensor-dependent branching, trace-escaping side effects — before they reach
the chip. Run it via ``make lint`` / ``python tools/lint_tpu.py <paths>``,
or programmatically:

    from paddle_tpu.analysis import lint_paths
    result = lint_paths(["paddle_tpu", "examples"])
    assert not result.violations

tpurace (ISSUE 19, also pure stdlib) extends the static side across
modules: thread-domain discovery + per-class attribute read/write census
over each domain's reachable call graph, reporting the TPL1500
thread-ownership family. ``lint_source`` folds in each file's slice;
the full cross-module sweep is ``make races`` / ``tools/race_tpu.py``
(:func:`analyze_paths`).

Runtime side: :func:`leak_guard` arms ``jax.check_tracer_leaks`` around a
compiled-path entry (opt-in via ``PADDLE_TPU_CHECK_TRACERS=1``);
:func:`ownership_guard` + :func:`guard_engine` arm cross-thread write
detection on the serving stack's shared objects (opt-in via
``PADDLE_TPU_CHECK_OWNERSHIP=1``), raising :class:`OwnershipError` where
tpurace's TPL1501 would point.
"""
from .linter import LintResult, Violation, lint_file, lint_paths, lint_source  # noqa: F401
from .ownership import OwnershipReport, analyze_paths, analyze_sources  # noqa: F401
from .rules import FAMILIES, RULES, Rule  # noqa: F401
from .runtime import (  # noqa: F401
    OwnershipError, TracerLeakError, guard_engine, guard_object,
    leak_guard, ownership_checks_enabled, ownership_guard, thread_domain,
    tracer_checks_enabled)

__all__ = [
    "LintResult", "Violation", "lint_file", "lint_paths", "lint_source",
    "RULES", "Rule", "FAMILIES",
    "OwnershipReport", "analyze_paths", "analyze_sources",
    "leak_guard", "tracer_checks_enabled", "TracerLeakError",
    "ownership_guard", "ownership_checks_enabled", "OwnershipError",
    "guard_object", "guard_engine", "thread_domain",
]
