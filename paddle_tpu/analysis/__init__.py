"""paddle_tpu.analysis — tpulint: trace-safety tooling for the compiled path.

Static side (pure stdlib, no jax import): an AST linter that finds
jit-breaking and recompile-forcing patterns — host syncs, impure RNG,
tensor-dependent branching, trace-escaping side effects — before they reach
the chip. Run it via ``make lint`` / ``python tools/lint_tpu.py <paths>``,
or programmatically:

    from paddle_tpu.analysis import lint_paths
    result = lint_paths(["paddle_tpu", "examples"])
    assert not result.violations

Runtime side: :func:`leak_guard` arms ``jax.check_tracer_leaks`` around a
compiled-path entry (opt-in via ``PADDLE_TPU_CHECK_TRACERS=1``).
"""
from .linter import LintResult, Violation, lint_file, lint_paths, lint_source  # noqa: F401
from .rules import FAMILIES, RULES, Rule  # noqa: F401
from .runtime import TracerLeakError, leak_guard, tracer_checks_enabled  # noqa: F401

__all__ = [
    "LintResult", "Violation", "lint_file", "lint_paths", "lint_source",
    "RULES", "Rule", "FAMILIES",
    "leak_guard", "tracer_checks_enabled", "TracerLeakError",
]
